"""Sequence parallelism: ring attention and Ulysses head-exchange.

The reference has no sequence/context parallelism (SURVEY.md §5 long-context
row: absent; scaling axis is the batch).  A complete TPU framework needs
long-context support as a first-class citizen, and the ICI torus is built
for it:

* **Ring attention** (`ring_attention`): K/V blocks rotate around the ``sp``
  ring via ``lax.ppermute`` (one ICI-neighbor hop per step) while each shard
  accumulates attention for its local queries with an online-softmax
  (running max / denominator), fp32 accumulators.  Communication is
  perfectly overlapped by XLA: the next block transfers while the current
  one is being used — the TPU-native equivalent of what the reference's
  background thread + streams did for allreduce overlap.
* **Ulysses** (`ulysses_attention`): one ``all_to_all`` turns
  sequence-sharding into head-sharding, full attention runs locally per
  head group, a second ``all_to_all`` restores sequence-sharding.  Cheaper
  for moderate sequence lengths; requires ``heads % sp_size == 0``.

Both are written for use inside ``shard_map`` bodies (axis names, like
``horovod_tpu.ops.collective``); ``make_sharded_attention`` wraps one in
``shard_map`` over a mesh for direct use.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel.shard import shard_map


def _online_block(q, k, v, m, l, acc, mask, scale):
    """One online-softmax accumulation step.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m/l: [B, H, Sq]; acc like q but
    fp32.  ``mask``: [Sq, Sk] boolean (True = attend) or None.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, -1e30)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    p = jnp.exp(s - m_new[..., None])
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    acc_new = acc * corr.transpose(0, 2, 1)[..., None] + \
        pv.astype(jnp.float32)
    return m_new, l_new, acc_new


def ring_attention(q, k, v, axis: str = "sp", causal: bool = True):
    """Blockwise ring attention over the ``axis`` ring (inside shard_map).

    q/k/v: [B, S_local, H, D] — the local sequence shard.  Returns the
    attention output [B, S_local, H, D] in q's dtype.  Softmax statistics
    are fp32; the result is exact (not an approximation) — identical to
    full attention on the gathered sequence, up to fp accumulation order.
    """
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    B, S, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]  # send to next neighbor

    tri = jnp.tril(jnp.ones((S, S), jnp.bool_))

    def _mask(owner):
        if not causal:
            return None
        # owner < my: attend fully; owner == my: causal triangle;
        # owner > my: fully masked.  Select via lax to stay traceable.
        full = jnp.ones((S, S), jnp.bool_)
        none = jnp.zeros((S, S), jnp.bool_)
        return lax.select(
            owner < my, full, lax.select(owner == my, tri, none))

    # Step 0 is the self-block (no hop); steps 1..n-1 each hop K/V one
    # neighbor before use, so exactly n-1 ppermutes happen in total.
    m0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    acc0 = jnp.zeros((B, S, H, D), jnp.float32)
    m, l, acc = _online_block(q, k, v, m0, l0, acc0, _mask(my), scale)

    def body(step, carry):
        k_cur, v_cur, m, l, acc = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        # After `step` hops we hold the block of rank (my - step) mod n.
        owner = (my - step) % n
        m, l, acc = _online_block(q, k_cur, v_cur, m, l, acc,
                                  _mask(owner), scale)
        return k_cur, v_cur, m, l, acc

    _, _, m, l, acc = lax.fori_loop(1, n, body, (k, v, m, l, acc))
    out = acc / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sp", causal: bool = True):
    """Ulysses sequence parallelism: all-to-all head exchange (inside
    shard_map).  q/k/v: [B, S_local, H, D] with H divisible by the axis
    size; returns [B, S_local, H, D]."""
    n = lax.axis_size(axis)
    B, S, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"heads {H} not divisible by axis size {n}")

    def seq_to_heads(x):
        # [B, S_local, H, D] -> [B, S_global, H/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    Sg = qg.shape[1]
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", qg, kg).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((Sg, Sg), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(vg.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vg)
    return heads_to_seq(out)


def full_attention(q, k, v, causal: bool = True):
    """Single-device reference attention (the oracle for tests)."""
    B, S, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def make_sharded_attention(mesh, impl: str = "ring", axis: str = "sp",
                           causal: bool = True,
                           head_axis: Optional[str] = None):
    """Wrap ring/ulysses attention in shard_map over ``mesh``.

    Returns ``fn(q, k, v) -> out`` taking/returning global [B, S, H, D]
    arrays sequence-sharded over ``axis``, batch over ``dp`` when the mesh
    has it, and heads over ``head_axis`` when given (tensor parallelism
    composed with sequence parallelism).
    """
    fns = {"ring": ring_attention, "ulysses": ulysses_attention}
    if impl not in fns:
        raise ValueError(f"impl must be one of {sorted(fns)}")
    if head_axis is not None and head_axis not in mesh.shape:
        head_axis = None
    inner = functools.partial(fns[impl], axis=axis, causal=causal)
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = P(batch_ax, axis, head_axis, None)

    def fn(q, k, v):
        return shard_map(inner, mesh, in_specs=(spec, spec, spec),
                         out_specs=spec)(q, k, v)

    return fn
