"""Device-mesh construction and TPU topology discovery.

This is the TPU-native replacement for the reference's communicator split
(``horovod/common/mpi/mpi_context.cc:147-160`` builds GLOBAL / LOCAL / CROSS
MPI communicators; NCCL forms per-node cliques in
``nccl_operations.cc:59-92``).  On TPU the same three-way split falls out of
the physical fabric:

* ``dp``   — data-parallel axis (the only axis the reference has),
* ``ici``  — devices sharing an ICI slice (reference: LOCAL / intra-node),
* ``dcn``  — slices connected over data-center network (reference: CROSS).

plus model axes (``tp``, ``pp``, ``sp``, ``ep``) the reference never had but
which a complete TPU framework must carry (SURVEY.md §5 long-context note).

Everything here is plain ``jax.sharding`` — collectives are inserted by XLA
from shardings + ``shard_map`` axis names, never hand-scheduled.
"""

from __future__ import annotations

import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

# Canonical axis names.  Order matters: leftmost axes change slowest across
# the device list, so putting ``dcn``/``pp`` first keeps their collectives on
# the slow links and lets ``tp``/``sp`` ride adjacent-ICI neighbors.
DATA_AXIS = "dp"
MODEL_AXIS = "tp"
PIPELINE_AXIS = "pp"
SEQUENCE_AXIS = "sp"
EXPERT_AXIS = "ep"
CROSS_AXIS = "dcn"

_ALL_AXES = (CROSS_AXIS, PIPELINE_AXIS, DATA_AXIS, EXPERT_AXIS,
             SEQUENCE_AXIS, MODEL_AXIS)


def num_slices() -> int:
    """Number of ICI slices (DCN-connected groups) visible to this process.

    Reads JAX device attributes when available (``slice_index`` on real TPU
    pods); virtual/CPU devices report one slice.
    """
    import jax

    idx = set()
    for d in jax.devices():
        idx.add(getattr(d, "slice_index", 0))
    return max(1, len(idx))


def _factor_remaining(total: int, sizes: Dict[str, int]) -> Dict[str, int]:
    """Fill in any axis size given as -1 so the product matches ``total``."""
    known = 1
    unknown = None
    for name, s in sizes.items():
        if s == -1:
            if unknown is not None:
                raise ValueError("at most one axis may be -1")
            unknown = name
        else:
            known *= s
    if unknown is not None:
        if total % known != 0:
            raise ValueError(
                f"cannot infer axis {unknown!r}: {total} devices not "
                f"divisible by {known}")
        sizes = dict(sizes)
        sizes[unknown] = total // known
    return sizes


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence] = None,
    allow_split_physical_axes: bool = True,
):
    """Build a ``jax.sharding.Mesh``.

    ``axes`` maps axis name -> size; one size may be ``-1`` (inferred).  With
    no arguments you get a pure data-parallel mesh over every device — the
    Horovod default (one DP rank per chip).

    On real TPU hardware ``jax.experimental.mesh_utils`` picks a device
    order that keeps each named axis on physically adjacent chips so XLA's
    collectives ride ICI rings; on CPU test meshes we fall back to a plain
    reshape.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if axes is None:
        axes = {DATA_AXIS: n}
    axes = _factor_remaining(n, dict(axes))
    sizes = list(axes.values())
    names = list(axes.keys())
    if math.prod(sizes) != n:
        raise ValueError(
            f"mesh axes {axes} require {math.prod(sizes)} devices, "
            f"have {n}")

    platform = devices[0].platform if devices else "cpu"
    if platform == "tpu":
        from jax.experimental import mesh_utils

        try:
            dev_array = mesh_utils.create_device_mesh(
                sizes, devices=list(devices),
                allow_split_physical_axes=allow_split_physical_axes)
        except Exception:
            dev_array = np.array(list(devices)).reshape(sizes)
    else:
        dev_array = np.array(list(devices)).reshape(sizes)
    return jax.sharding.Mesh(dev_array, names)


def make_hierarchical_mesh(
    *,
    devices: Optional[Sequence] = None,
    inner_axes: Optional[Dict[str, int]] = None,
):
    """Mesh with an explicit ``dcn`` outer axis over ICI slices.

    TPU analog of the reference's hierarchical allreduce topology
    (``nccl_operations.cc:163-354``: NCCL within a node, MPI across): the
    ``dcn`` axis spans slices, remaining axes span the chips of one slice.
    On a single slice this degenerates to ``dcn=1`` so code written against
    the hierarchical mesh runs unchanged everywhere.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    slices: Dict[int, List] = {}
    for d in devices:
        slices.setdefault(getattr(d, "slice_index", 0), []).append(d)
    n_slices = len(slices)
    per = len(devices) // n_slices
    if inner_axes is None:
        inner_axes = {DATA_AXIS: per}
    inner_axes = _factor_remaining(per, dict(inner_axes))
    ordered = []
    for k in sorted(slices):
        ordered.extend(slices[k])
    sizes = [n_slices] + list(inner_axes.values())
    names = [CROSS_AXIS] + list(inner_axes.keys())
    dev_array = np.array(ordered).reshape(sizes)
    return jax.sharding.Mesh(dev_array, names)


def mesh_axis_size(mesh, name: str) -> int:
    return mesh.shape.get(name, 1)


def filter_spec(spec, mesh):
    """Drop PartitionSpec axes that are not in ``mesh`` (→ None).

    Lets models annotate the full axis vocabulary (dp/tp/sp/ep/…) while
    running on meshes that carry any subset.  Handles tuple entries
    (sharding one dim over several axes) by filtering within the tuple.
    """
    from jax.sharding import PartitionSpec as P

    def keep(ax):
        if ax is None:
            return None
        if isinstance(ax, (tuple, list)):
            kept = tuple(a for a in ax if a in mesh.shape)
            return kept if kept else None
        return ax if ax in mesh.shape else None

    return P(*[keep(ax) for ax in spec])


def sharding_for(mesh, spec):
    """``NamedSharding`` for ``spec`` on ``mesh`` with axes the mesh
    doesn't carry dropped (``filter_spec``) — the one-liner every
    consumer of a full-vocabulary spec ends up writing (e.g. the serving
    KV caches, serving/decode.py)."""
    import jax

    return jax.sharding.NamedSharding(mesh, filter_spec(spec, mesh))


def data_parallel_axes(mesh) -> Tuple[str, ...]:
    """Axes that carry gradient reduction: every mesh axis that is a
    replication axis for parameters (dp, dcn and ep-for-non-expert params
    are handled by callers; default is dp + dcn when present)."""
    out = []
    for ax in (CROSS_AXIS, DATA_AXIS):
        if ax in mesh.shape:
            out.append(ax)
    return tuple(out)
