"""Parallelism layer: meshes, sharded steps, distributed optimizers.

TPU-native scaling machinery (SPMD over ``jax.sharding.Mesh``): data
parallelism (the reference's only axis), plus tensor / pipeline / sequence
/ expert axes and hierarchical ICI+DCN reduction, which complete the
framework for modern model scale (SURVEY.md §5 long-context note).
"""

from horovod_tpu.parallel.mesh import (  # noqa: F401
    CROSS_AXIS,
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    PIPELINE_AXIS,
    SEQUENCE_AXIS,
    data_parallel_axes,
    make_hierarchical_mesh,
    make_mesh,
    mesh_axis_size,
    num_slices,
)
from horovod_tpu.parallel.optimizer import (  # noqa: F401
    DistributedOptimizer,
    allreduce_gradients,
    distributed_grad,
    distributed_value_and_grad,
)
