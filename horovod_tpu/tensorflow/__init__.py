"""TensorFlow 2 front-end: ``import horovod_tpu.tensorflow as hvd``.

Role parity: ``horovod/tensorflow/__init__.py`` + ``tensorflow/mpi_ops.py``
— allreduce/allgather/broadcast on tf tensors with gradient support,
``broadcast_variables``, ``DistributedGradientTape``, and a Keras-3
``DistributedOptimizer`` (the reference's TF custom ops become
``tf.py_function`` bridges into the shared coordination engine: the op
executes eagerly at graph runtime, so the same engine serves eager code
and compiled ``tf.function`` graphs).
"""

from __future__ import annotations

import tensorflow as tf

from horovod_tpu.basics import (  # noqa: F401
    cache_stats,
    cross_rank,
    cross_size,
    cuda_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu import basics
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.ops import eager as _eager
from horovod_tpu.ops.eager import _auto_name, _resolve_op

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM
Min = ReduceOp.MIN
Max = ReduceOp.MAX
Product = ReduceOp.PRODUCT


class Compression:
    """fp16-on-the-wire gradient compression (parity:
    tensorflow/compression.py)."""

    class none:
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class fp16:
        @staticmethod
        def compress(t):
            if t.dtype.is_floating and t.dtype != tf.float16:
                return tf.cast(t, tf.float16), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return tf.cast(t, ctx) if ctx is not None else t


def _engine_call(fn, x, out_dtype):
    """Run an engine collective on a tf tensor; works in eager mode and
    inside tf.function (py_function escapes the graph at runtime, which
    is exactly where the reference's AsyncOpKernel enqueued)."""
    y = tf.py_function(lambda v: fn(v.numpy()), [x], out_dtype)
    return y


def _native_kernels(x, process_set):
    """(op_library, ps_id, ps_size) when the C++ custom kernels
    (csrc/tf_ops.cc — real graph ops into the native engine, the
    reference's mpi_ops.cc mechanism) can serve this tensor, else
    (None, 0, 0) and the py_function path runs."""
    from horovod_tpu.tensorflow import _native_ops

    if x.dtype.name not in _native_ops.SUPPORTED_DTYPES:
        return None, 0, 0
    nlib = _native_ops.lib()
    if nlib is None:
        return None, 0, 0
    ps_id, ps_size = 0, 0
    if process_set is not None:
        ps_id, ps_size = process_set.validate(rank(), size())
    return nlib, ps_id, ps_size


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=Compression.none, op=None, name=None,
              process_set=None):
    """Differentiable allreduce of a tf.Tensor (or IndexedSlices, which
    gather values+indices like the reference, tensorflow/__init__.py:74)."""
    if isinstance(tensor, tf.IndexedSlices):
        # Sparse gradient path: allgather values and indices (over the
        # process set when given — a silently-global gather would
        # deadlock set members against non-members).  The indices gather
        # is control-chained behind the values gather: both kernels are
        # synchronous, so two ranks whose executors pick opposite orders
        # for these independent nodes would block each other forever
        # (see grouped_allreduce).
        values = allgather(tensor.values, name=f"{name}.values"
                           if name else None, process_set=process_set)
        with tf.control_dependencies([values]):
            indices = allgather(tensor.indices, name=f"{name}.indices"
                                if name else None,
                                process_set=process_set)
        rop = _resolve_op(op, average)
        if rop == ReduceOp.AVERAGE:
            values = values / (process_set.size()
                               if process_set is not None else size())
        return tf.IndexedSlices(values, indices,
                                dense_shape=tensor.dense_shape)
    rop = _resolve_op(op, average)
    nm = _auto_name("tf.allreduce", name)
    compressed, ctx = compression.compress(tf.convert_to_tensor(tensor))

    @tf.custom_gradient
    def _fn(x):
        nlib, ps_id, ps_size = _native_kernels(x, process_set)
        if nlib is not None:
            y = nlib.hvd_allreduce(
                x, tensor_name=nm, reduce_op=int(rop),
                process_set_id=ps_id, process_set_size=ps_size)
        else:
            y = _engine_call(
                lambda v: _eager.allreduce(v, op=rop, name=nm,
                                           process_set=process_set),
                x, x.dtype)
            # The engine flattens 0-d scalars to shape (1,); restore.
            y = tf.reshape(y, tf.shape(x))
            y.set_shape(x.shape)

        def grad(dy):
            # Derived (trace-time) names keep every rank's runtime naming
            # identical even when TF executes py_functions concurrently.
            return allreduce(dy, op=rop, name=f"{nm}.grad",
                             process_set=process_set)

        return y, grad

    return compression.decompress(_fn(compressed), ctx)


def grouped_allreduce(tensors, average=None, name=None,
                      compression=Compression.none, op=None,
                      process_set=None):
    """Allreduce a list of dense tensors through ONE graph node that
    submits every tensor to the engine before waiting on any result.

    This is not just a fusion aid — it is the deadlock-safe way to
    reduce a set of gradients.  The per-tensor collective kernels are
    synchronous (py_function and csrc/tf_ops.cc both enqueue-and-wait),
    and TF executes independent graph nodes in arbitrary,
    scheduler-dependent order: under a small executor thread pool two
    ranks can each block inside a *different* tensor's collective and
    starve the submissions the peer is waiting for (observed as the
    stall inspector reporting e.g. ``do.2 ready on [1]`` / ``do.4 ready
    on [0]`` forever).  One grouped node makes each rank's submission
    set atomic, so scheduling order cannot split it.  (The reference
    grew ``hvd.grouped_allreduce`` one release after v0.19 for the
    fusion half of this story.)

    Differentiable: the gradient is the grouped allreduce of the
    upstream gradients under the same op (the grouped twin of
    ``allreduce``'s registered gradient).
    """
    if not tensors:
        return []
    rop = _resolve_op(op, average)
    base = _auto_name("tf.grouped_allreduce", name)
    xs = [tf.convert_to_tensor(t) for t in tensors]
    comp = [compression.compress(x) for x in xs]
    cxs = [c for c, _ in comp]

    @tf.custom_gradient
    def _fn(*cxs):
        from horovod_tpu.tensorflow import _native_ops

        nlib, ps_id, ps_size = _native_kernels(cxs[0], process_set)
        if nlib is not None and hasattr(nlib, "hvd_grouped_allreduce") \
                and all(c.dtype.name in _native_ops.SUPPORTED_DTYPES
                        for c in cxs):
            # One variadic C++ kernel: enqueue-all-then-wait inside the
            # op (csrc/tf_ops.cc::HvdGroupedAllreduceOp) — same
            # atomic-submission guarantee, no py_function/numpy hop.
            ys = nlib.hvd_grouped_allreduce(
                list(cxs), tensor_name=base, reduce_op=int(rop),
                process_set_id=ps_id, process_set_size=ps_size)
        else:
            def _py(*arrs):
                outs = _eager.grouped_allreduce(
                    [a.numpy() for a in arrs], op=rop, name=base,
                    process_set=process_set)
                return list(outs)

            ys = tf.py_function(_py, list(cxs), [c.dtype for c in cxs])
        if len(cxs) == 1:
            ys = [ys] if tf.is_tensor(ys) else list(ys)
        fixed = []
        for y, cx in zip(ys, cxs):
            # The engine flattens 0-d scalars to shape (1,); restore.
            y = tf.reshape(y, tf.shape(cx))
            y.set_shape(cx.shape)
            fixed.append(y)

        def grad(*dys):
            # An unused output arrives as dy=None; it must still ride
            # the grouped collective (every rank submits the same set),
            # so substitute zeros.
            dys = [tf.zeros_like(cx) if d is None else d
                   for d, cx in zip(dys, cxs)]
            return grouped_allreduce(dys, op=rop, name=f"{base}.grad",
                                     process_set=process_set)

        return tuple(fixed), grad

    ys = _fn(*cxs)
    if tf.is_tensor(ys):
        ys = [ys]
    return [compression.decompress(y, ctx)
            for y, (_, ctx) in zip(ys, comp)]


def allgather(tensor, name=None, process_set=None):
    """Differentiable allgather: concat along dim 0 (ragged first dims
    allowed); backward reduces and extracts this rank's segment."""
    nm = _auto_name("tf.allgather", name)
    x = tf.convert_to_tensor(tensor)
    dim0 = tf.shape(x)[0]

    @tf.custom_gradient
    def _fn(x):
        nlib, ps_id, ps_size = _native_kernels(x, process_set)
        if nlib is not None:
            y = nlib.hvd_allgather(
                x, tensor_name=nm, process_set_id=ps_id,
                process_set_size=ps_size)
        else:
            y = _engine_call(
                lambda v: _eager.allgather(v, name=nm,
                                           process_set=process_set),
                x, x.dtype)
        y.set_shape(tf.TensorShape([None]).concatenate(x.shape[1:]))

        def grad(dy):
            reduced = allreduce(dy, op=ReduceOp.SUM, name=f"{nm}.grad",
                                process_set=process_set)
            sizes = _engine_call(
                lambda v: _eager.allgather(v, name=f"{nm}.grad.sizes",
                                           process_set=process_set),
                tf.reshape(dim0, [1]), tf.int32)
            my_pos = process_set.rank() if process_set is not None \
                else rank()
            offset = tf.reduce_sum(sizes[:my_pos])
            return reduced[offset:offset + dim0]

        return y, grad

    return _fn(x)


def reducescatter(tensor, average=None, name=None, op=None,
                  process_set=None):
    """Differentiable reducescatter: reduce across ranks, scatter over
    dim 0 (rank r receives the r-th near-equal row chunk; the reference
    project added ``hvd.reducescatter`` right after the v0.19 line).
    Backward is the allgather of the per-rank chunk gradients (scaled by
    1/size for Average), mirroring the reference's grad registration."""
    nm = _auto_name("tf.reducescatter", name)
    x = tf.convert_to_tensor(tensor)
    rop = _resolve_op(op, average)
    if rop not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        # The registered gradient (allgather) is the Sum/Average
        # adjoint; Min/Max/Product would need a subgradient and are not
        # in the reference's TF surface either.
        raise ValueError(
            f"tf reducescatter supports Sum/Average, got {rop}")

    @tf.custom_gradient
    def _fn(x):
        y = _engine_call(
            lambda v: _eager.reducescatter(v, name=nm, op=rop,
                                           process_set=process_set),
            x, x.dtype)
        y.set_shape(tf.TensorShape([None]).concatenate(x.shape[1:]))

        def grad(dy):
            g = _engine_call(
                lambda v: _eager.allgather(v, name=f"{nm}.grad",
                                           process_set=process_set),
                dy, dy.dtype)
            g.set_shape(x.shape)
            if rop == ReduceOp.AVERAGE:
                g = g / (process_set.size() if process_set is not None
                         else size())
            return g

        return y, grad

    return _fn(x)


def broadcast(tensor, root_rank=0, name=None, process_set=None):
    """Differentiable broadcast from root; backward sums to root."""
    nm = _auto_name("tf.broadcast", name)

    @tf.custom_gradient
    def _fn(x):
        nlib, ps_id, ps_size = _native_kernels(x, process_set)
        if nlib is not None:
            y = nlib.hvd_broadcast(
                x, tensor_name=nm, root_rank=root_rank,
                process_set_id=ps_id, process_set_size=ps_size)
        else:
            y = _engine_call(
                lambda v: _eager.broadcast(v, root_rank=root_rank,
                                           name=nm,
                                           process_set=process_set),
                x, x.dtype)
            # The engine flattens 0-d scalars to shape (1,); restore.
            y = tf.reshape(y, tf.shape(x))
            y.set_shape(x.shape)

        def grad(dy):
            reduced = allreduce(dy, op=ReduceOp.SUM, name=f"{nm}.grad",
                                process_set=process_set)
            if rank() == root_rank:
                return reduced
            return reduced * 0

        return y, grad

    return _fn(tf.convert_to_tensor(tensor))


def alltoall(tensor, splits=None, name=None, process_set=None):
    nm = _auto_name("tf.alltoall", name)
    x = tf.convert_to_tensor(tensor)
    if splits is None:
        return _engine_call(
            lambda v: _eager.alltoall(v, name=nm,
                                      process_set=process_set),
            x, x.dtype)
    sp = [int(s) for s in splits]
    data, recv = tf.py_function(
        lambda v: _eager.alltoall(v.numpy(), splits=sp, name=nm,
                                  process_set=process_set),
        [x], [x.dtype, tf.int64])
    return data, recv


def join():
    return basics._engine().join()


def barrier(process_set=None):
    basics._engine().barrier(process_set=process_set)


def broadcast_object(obj, root_rank=0, name=None):
    return _eager.broadcast_object(obj, root_rank, name)


def broadcast_variables(variables, root_rank=0, process_set=None):
    """Assigns every variable the root's value (parity:
    tensorflow/__init__.py:139 broadcast_variables)."""
    for i, v in enumerate(variables):
        v.assign(broadcast(v, root_rank, name=f"bv.{i}",
                           process_set=process_set))


def BroadcastGlobalVariablesHook(root_rank=0, device=""):
    """Parity surface for the reference's TF1 ``SessionRunHook``
    (tensorflow/__init__.py:194).  TF1 sessions are not part of the TF2
    front-end; the equivalents are :func:`broadcast_variables` after the
    first step, or ``horovod_tpu.keras.callbacks
    .BroadcastGlobalVariablesCallback`` for Keras training loops."""
    raise NotImplementedError(
        "TF1 session hooks are not supported by the TF2 front-end; call "
        "broadcast_variables(model.variables, root_rank) after the first "
        "training step, or use horovod_tpu.keras.callbacks."
        "BroadcastGlobalVariablesCallback with model.fit().")


def _reduce_gradients(grads, base, op, compression, process_set):
    """Shared gradient-reduction path for the optimizer and tape
    wrappers: dense gradients ride one grouped submission (deadlock-safe
    and coordinator-fusible, see ``grouped_allreduce``); sparse
    IndexedSlices follow, control-chained behind the dense results and
    each other so every blocking collective node has the same total
    order on every rank.  ``None`` gradients pass through."""
    reduced = list(grads)
    dense_ix = [i for i, g in enumerate(grads)
                if g is not None and not isinstance(g, tf.IndexedSlices)]
    if dense_ix:
        douts = grouped_allreduce(
            [grads[i] for i in dense_ix], op=op, compression=compression,
            name=base, process_set=process_set)
        for i, o in zip(dense_ix, douts):
            reduced[i] = o
    anchor = [reduced[dense_ix[-1]]] if dense_ix else []
    for i, g in enumerate(grads):
        if g is None or not isinstance(g, tf.IndexedSlices):
            continue
        with tf.control_dependencies(anchor):
            reduced[i] = allreduce(g, op=op, compression=compression,
                                   name=f"{base}.{i}",
                                   process_set=process_set)
        # Anchor on the LAST collective of this sparse gradient (the
        # indices gather, which is itself chained behind the values
        # gather) — anchoring on .values would leave indices(i) and
        # values(i+1) mutually unordered, the deadlock shape again.
        anchor = [reduced[i].indices]
    return reduced


class DistributedGradientTape:
    """Wraps a ``tf.GradientTape`` so ``gradient()`` allreduces the
    results (parity: tensorflow/__init__.py:474-531 — same wrap-an-
    existing-tape contract: ``tape = hvd.DistributedGradientTape(tape)``).
    Can also be used directly as a context manager, in which case it
    owns a fresh tape."""

    def __init__(self, gradtape=None, device_dense="", device_sparse="",
                 compression=Compression.none, op=ReduceOp.AVERAGE,
                 persistent=False, watch_accessed_variables=True,
                 process_set=None):
        self._tape = gradtape if gradtape is not None else tf.GradientTape(
            persistent=persistent,
            watch_accessed_variables=watch_accessed_variables)
        self._compression = compression
        self._op = op
        self._process_set = process_set

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def __getattr__(self, item):
        # watch, watched_variables, jacobian, ... delegate to the tape
        return getattr(self._tape, item)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        single = not isinstance(grads, (list, tuple))
        if single:
            grads = [grads]
        reduced = _reduce_gradients(grads, "dgt", self._op,
                                    self._compression, self._process_set)
        return reduced[0] if single else reduced


def DistributedOptimizer(optimizer, name=None,
                         compression=Compression.none,
                         op=ReduceOp.AVERAGE,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=False,
                         process_set=None,
                         device_dense="", device_sparse="",
                         sparse_as_dense=False, use_locking=False):
    """Wraps a Keras-3 optimizer: gradients are allreduced before being
    applied (parity: tensorflow/__init__.py:266-311 — there via
    compute_gradients; Keras 3 funnels through apply_gradients).

    ``op=Adasum`` selects the delta-model wrapper (parity:
    ``_DistributedAdasumOptimizer``, tensorflow/__init__.py:313-407):
    the local optimizer applies its update, the parameter *deltas* are
    combined with scale-invariant Adasum, and variables are reset to
    ``start + adasum(deltas)``.

    The instance is re-classed in place (same dynamic-subclass technique
    as the reference) so restored slot state and the iteration counter
    survive — important when wrapping an optimizer loaded from a
    checkpoint.

    ``device_dense``/``device_sparse``/``sparse_as_dense``/
    ``use_locking`` are accepted for reference signature compatibility
    and ignored — there are no CUDA streams or TF1 locking semantics to
    configure on this stack.

    ``backward_passes_per_step=N`` aggregates gradients locally over N
    ``apply_gradients`` calls and allreduces+applies only on the Nth
    (parity: ``LocalGradientAggregationHelper``, the reference's
    tensorflow/__init__.py:443 path); skipped calls leave the variables
    and slots untouched.  ``average_aggregated_gradients`` divides the
    local sum by N before the allreduce, as in the reference."""
    base_cls = optimizer.__class__
    _op = op
    _compression = compression
    _ps = process_set
    _bpps = int(backward_passes_per_step)
    _avg_agg = average_aggregated_gradients
    if _bpps < 1:
        raise ValueError(
            f"backward_passes_per_step must be >= 1, got {_bpps}")

    if op == ReduceOp.ADASUM:
        if process_set is not None:
            raise ValueError("Adasum does not support process sets")
        if _bpps != 1:
            raise ValueError(
                "backward_passes_per_step > 1 is incompatible with the "
                "Adasum delta-model wrapper (the delta must be computed "
                "per applied step)")
        class _WrappedAdasum(base_cls):
            def apply_gradients(self, grads_and_vars, *args, **kwargs):
                gv = list(grads_and_vars)
                tvars = [v for _, v in gv]
                starts = [tf.identity(v) for v in tvars]
                result = super().apply_gradients(gv, *args, **kwargs)
                # One grouped submission for all deltas — same deadlock
                # rationale as the Sum/Average path (grouped_allreduce).
                deltas = [tf.convert_to_tensor(v) - s
                          for v, s in zip(tvars, starts)]
                reduced = grouped_allreduce(
                    deltas, op=ReduceOp.ADASUM, name="adasum.delta",
                    compression=_compression)
                for v, s, d in zip(tvars, starts, reduced):
                    v.assign(s + d)
                return result

        _WrappedAdasum.__name__ = f"DistributedAdasum{base_cls.__name__}"
        optimizer.__class__ = _WrappedAdasum
        return optimizer

    class _Wrapped(base_cls):
        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            sup = super()
            grads_and_vars = list(grads_and_vars)
            grads = [g for g, _ in grads_and_vars]
            tvars = [v for _, v in grads_and_vars]

            def _reduce_apply(gs):
                reduced = _reduce_gradients(
                    gs, "do", _op, _compression, _ps)
                return sup.apply_gradients(
                    zip(reduced, tvars), *args, **kwargs)

            if _bpps == 1:
                return _reduce_apply(grads)

            # Graph-compatible local aggregation (reference:
            # LocalGradientAggregationHelper — tf.Variable state +
            # tf.cond, so a tf.function-compiled train step re-evaluates
            # the pass counter at run time instead of baking the
            # trace-time branch in).  Accumulators are created under
            # init_scope so the first call may itself be inside a trace;
            # object.__setattr__ sidesteps Keras's attribute tracking,
            # which wraps plain lists in copies.
            if getattr(self, "_hvd_agg_acc", None) is None:
                with tf.init_scope():
                    # One accumulator per variable regardless of the
                    # first call's None pattern: a head untouched by the
                    # first microbatch must still aggregate later ones
                    # (its untouched accumulator contributes zeros).
                    accs = [tf.Variable(tf.zeros_like(v), trainable=False)
                            for v in tvars]
                    counter = tf.Variable(0, dtype=tf.int64,
                                          trainable=False)
                object.__setattr__(self, "_hvd_agg_acc", accs)
                object.__setattr__(self, "_hvd_agg_counter", counter)
            accs = self._hvd_agg_acc
            counter = self._hvd_agg_counter
            # Slot variables cannot be created inside a tf.cond branch;
            # force the lazy build before entering it.
            if hasattr(self, "build") and not getattr(self, "built", True):
                self.build(tvars)
            for a, g in zip(accs, grads):
                if g is not None:
                    a.assign_add(tf.convert_to_tensor(g))
            counter.assign_add(1)

            # The call's None pattern is static per trace: a variable
            # with no gradient HERE forwards None (exactly like the
            # bpps=1 path — no zero-tensor updates that would move
            # momentum/weight-decay state on untouched variables).  Its
            # accumulator is left intact, applying at the next Nth pass
            # where it does receive a gradient.
            has_g = [g is not None for g in grads]

            def _apply_branch():
                gs = [tf.convert_to_tensor(a) if has else None
                      for a, has in zip(accs, has_g)]
                if _avg_agg:
                    gs = [g / _bpps if g is not None else None
                          for g in gs]
                _reduce_apply(gs)
                for a, has in zip(accs, has_g):
                    if has:
                        a.assign(tf.zeros_like(a))
                return tf.constant(True)

            def _skip_branch():
                # Aggregation-only pass: no collective, no update.
                return tf.constant(False)

            return tf.cond(tf.equal(counter % _bpps, 0),
                           _apply_branch, _skip_branch)

    _Wrapped.__name__ = f"Distributed{base_cls.__name__}"
    optimizer.__class__ = _Wrapped
    return optimizer
