"""On-demand build + load of the TensorFlow custom-op library.

``csrc/tf_ops.cc`` registers ``HvdAllreduce`` / ``HvdBroadcast`` /
``HvdAllgather`` — real graph ops whose kernels enqueue straight into
the native C++ engine (the reference's ``tensorflow/mpi_ops.cc``
mechanism).  This module compiles that file against the installed
TensorFlow's headers the first time it is needed (dev checkouts with a
toolchain), caches ``horovod_tpu/_lib/libhvd_tf_ops.so``, and loads it
with ``tf.load_op_library``.

Falls back to ``None`` — and the front-end to its ``tf.py_function``
path — when any precondition is missing: the Python engine is active
(the kernels reach only the in-process C++ engine), no compiler, no
checkout sources and no prebuilt library, or ``HVD_TF_NATIVE_OPS=0``.
"""

from __future__ import annotations

import os
import subprocess
import threading

_lock = threading.Lock()
_lib = None
_failed = False

# Covered by the ops' type lists in tf_ops.cc; everything else takes
# the py_function path per tensor.
SUPPORTED_DTYPES = frozenset({
    "float32", "float64", "float16", "bfloat16", "int32", "int64",
    "uint8", "int8", "bool"})

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SO = os.path.join(_PKG_DIR, "_lib", "libhvd_tf_ops.so")
_CORE = os.path.join(_PKG_DIR, "_lib", "libhvd_core.so")
_CSRC = os.path.normpath(os.path.join(_PKG_DIR, os.pardir, "csrc"))


def lib():
    """The loaded op library, or None when the native path is off.

    Preconditions (engine type, env switch) re-evaluate on EVERY call —
    a collective issued before ``hvd.init()``, or an init→shutdown→
    re-init cycle onto a different engine, must not latch the fast path
    off for the process lifetime.  Only a genuine build/load failure
    latches (retrying a broken compile every op call would be worse).
    """
    global _lib, _failed
    if not _preconditions_ok():
        return None
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            _lib = _build_and_load()
        except Exception as e:
            _failed = True
            from horovod_tpu.utils.logging import get_logger

            get_logger().debug(f"tf native ops unavailable: {e}")
    return _lib


def _preconditions_ok() -> bool:
    if os.environ.get("HVD_TF_NATIVE_OPS", "1") == "0":
        return False
    try:
        from horovod_tpu import basics
        from horovod_tpu.runtime_native import NativeEngine

        # Single-process / py engines never create the C++ engine the
        # kernels enqueue into.
        return isinstance(basics._engine(), NativeEngine)
    except Exception:
        return False


def _build_and_load():
    import tensorflow as tf

    src = os.path.join(_CSRC, "tf_ops.cc")
    if _needs_build(src):
        _build(tf, src)
    if not os.path.exists(_SO):
        raise RuntimeError(f"{_SO} not built and no sources to build it")
    return tf.load_op_library(_SO)


def _needs_build(src: str) -> bool:
    if not os.path.isfile(src):
        return False  # wheel install: use the prebuilt .so or fall back
    if not os.path.exists(_SO):
        return True
    newest = max(os.path.getmtime(p) for p in (
        src, os.path.join(_CSRC, "engine.h"), _CORE))
    return os.path.getmtime(_SO) < newest


def _build(tf, src: str) -> None:
    # Gang-safe: every local rank may race to build; compile to a
    # per-pid temp and atomically publish, so loaders only ever see a
    # complete library.
    tmp = f"{_SO}.tmp.{os.getpid()}"
    cmd = ["g++", "-O2", "-std=c++17", "-fPIC", "-w",
           f"-I{_CSRC}",
           *tf.sysconfig.get_compile_flags(),
           "-shared", src,
           f"-L{os.path.dirname(_CORE)}", "-l:libhvd_core.so",
           "-Wl,-rpath,$ORIGIN",
           *tf.sysconfig.get_link_flags(),
           "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=600)
        if r.returncode != 0:
            raise RuntimeError(f"tf_ops build failed: {r.stderr[-800:]}")
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
