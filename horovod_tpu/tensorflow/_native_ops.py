"""On-demand build + load of the TensorFlow custom-op library.

``csrc/tf_ops.cc`` registers ``HvdAllreduce`` / ``HvdBroadcast`` /
``HvdAllgather`` — real graph ops whose kernels enqueue straight into
the native C++ engine (the reference's ``tensorflow/mpi_ops.cc``
mechanism).  Built on demand against the installed TensorFlow's headers
via the shared machinery in ``horovod_tpu.common.native_build``;
``HVD_TF_NATIVE_OPS=0`` opts out.

Preconditions (engine type, env switch) re-evaluate on EVERY call — a
collective issued before ``hvd.init()``, or an init→shutdown→re-init
cycle onto a different engine, must not latch the fast path off for the
process lifetime.  Only a genuine build/load failure latches (retrying
a broken compile every op call would be worse).  Falls back to ``None``
— and the front-end to its ``tf.py_function`` path — whenever any
precondition is missing.
"""

from __future__ import annotations

import os
import threading

from horovod_tpu.common import native_build

_lock = threading.Lock()
_lib = None
_failed = False

# Covered by the ops' type lists in tf_ops.cc; everything else takes
# the py_function path per tensor.
SUPPORTED_DTYPES = frozenset({
    "float32", "float64", "float16", "bfloat16", "int32", "int64",
    "uint8", "int8", "bool"})

_SO = os.path.join(native_build.LIB_DIR, "libhvd_tf_ops.so")


def lib():
    """The loaded op library, or None when the native path is off."""
    global _lib, _failed
    if os.environ.get("HVD_TF_NATIVE_OPS", "1") == "0":
        return None
    if not native_build.native_engine_active():
        return None
    if _lib is not None or _failed:
        return _lib
    with _lock:
        if _lib is not None or _failed:
            return _lib
        try:
            _lib = _build_and_load()
        except Exception as e:
            _failed = True
            from horovod_tpu.utils.logging import get_logger

            get_logger().debug(f"tf native ops unavailable: {e}")
    return _lib


def _build_and_load():
    import tensorflow as tf

    src = os.path.join(native_build.CSRC_DIR, "tf_ops.cc")
    if native_build.needs_build(src, _SO):
        native_build.build(
            src, _SO,
            extra_flags=tf.sysconfig.get_compile_flags(),
            extra_links=tf.sysconfig.get_link_flags())
    if not os.path.exists(_SO):
        raise RuntimeError(f"{_SO} not built and no sources to build it")
    return tf.load_op_library(_SO)
