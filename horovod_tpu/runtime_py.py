"""Pure-Python process-group engine: controller + CPU data plane.

This is a complete, wire-compatible implementation of the coordination
protocol that the native C++ core (``csrc/``) also implements; it serves as
(a) the always-available fallback when the extension is not built, and
(b) the executable specification the native core is tested against.

Behavioral parity map (reference → here):
* ``horovod/common/operations.cc:333-589`` BackgroundThreadLoop /
  RunLoopOnce            → ``PyEngine._background_loop`` / ``_run_loop_once``
* ``horovod/common/controller.cc:62-354`` ComputeResponseList
  (coordinator negotiation, rank-0 message table)
                          → ``_coordinator_cycle`` / ``_MessageTable``
* ``horovod/common/controller.cc:376-609`` ConstructResponse (mismatch
  checking)               → ``_construct_response``
* ``horovod/common/controller.cc:638-759`` FuseResponses
                          → ``_fuse_responses``
* ``horovod/common/tensor_queue.cc``        → ``_pending`` + ``_table``
* ``horovod/torch/handle_manager.h:31-42``  → ``HandleManager``
* ``horovod/common/stall_inspector.cc``     → ``_check_stalls``
* ``horovod/common/ops/gloo_operations.cc`` (CPU data plane)
                          → ``horovod_tpu.ops.cpu_backend`` (ring algorithms)

The controller is a star over TCP (workers → rank 0), like the reference's
coordinator; the data plane is a full mesh running ring collectives.  All
of it is host-network traffic — on TPU the performance path is the in-graph
XLA backend (``horovod_tpu.ops.collective``); this engine exists for
Horovod-style multi-process eager semantics and as the correctness oracle.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.common import wire
from horovod_tpu.common import response_cache as rcache
from horovod_tpu.common.types import (
    CollectiveTimeoutError,
    DataType,
    FencedError,
    RanksFailedError,
    ReduceOp,
    Request,
    RequestType,
    Response,
    ResponseType,
    Status,
    StatusType,
    TensorShape,
)
from horovod_tpu.common.types import dtype_from_numpy, dtype_to_numpy_name
from horovod_tpu import telemetry as _telemetry
from horovod_tpu.telemetry import blackbox as blackbox_mod
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.telemetry import trace as trace_mod
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import socketutil as su
from horovod_tpu.utils import timeline as timeline_mod
from horovod_tpu.utils.logging import get_logger

_OP_NAMES = {
    RequestType.ALLREDUCE: "ALLREDUCE",
    RequestType.ALLGATHER: "ALLGATHER",
    RequestType.BROADCAST: "BROADCAST",
    RequestType.ALLTOALL: "ALLTOALL",
    RequestType.JOIN: "JOIN",
    RequestType.BARRIER: "BARRIER",
    RequestType.REDUCESCATTER: "REDUCESCATTER",
}

# -- evict-and-replay retention ----------------------------------------
# When the gang aborts an in-flight fused reduction (CollectiveTimeout-
# Error), the survivors retain copies of the ORIGINAL inputs here —
# pack() copies into the fusion buffer and the ring mutates only that
# buffer, so entry.array is pristine at abort time.  The holder is
# module-level on purpose: the elastic wrapper tears the engine down
# and re-forms a new one, and the replay must survive that.
_replay_lock = threading.Lock()
_replay_batch: Optional[List[dict]] = None


def retain_aborted_batch(batch: List[dict]) -> None:
    global _replay_batch
    with _replay_lock:
        _replay_batch = batch


def take_retained_batch() -> Optional[List[dict]]:
    """Pop the retained aborted batch (None when nothing was aborted).
    Each item: {name, array (copy), op, prescale, postscale}."""
    global _replay_batch
    with _replay_lock:
        batch, _replay_batch = _replay_batch, None
    return batch


class HandleManager:
    """Async handle table; parity: torch/handle_manager.h:31-42."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._next = 0
        self._status: Dict[int, Optional[Status]] = {}
        self._result: Dict[int, object] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._status[h] = None
            return h

    def mark_done(self, handle: int, status: Status, result=None) -> None:
        with self._cv:
            self._status[handle] = status
            self._result[handle] = result
            self._cv.notify_all()

    def poll(self, handle: int) -> bool:
        with self._lock:
            if handle not in self._status:
                raise ValueError(f"unknown handle {handle}")
            return self._status[handle] is not None

    def wait(self, handle: int, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._status.get(handle) is None:
                remaining = None if deadline is None else max(
                    0.0, deadline - time.monotonic())
                if deadline is not None and remaining == 0.0:
                    raise TimeoutError(f"handle {handle} timed out")
                self._cv.wait(remaining)
            status = self._status.pop(handle)
            result = self._result.pop(handle, None)
        if not status.ok_():
            if status.exc is not None:
                # Typed failure (e.g. CollectiveTimeoutError) — the
                # elastic wrapper dispatches on the exception class.
                raise status.exc
            raise RuntimeError(status.reason or "collective failed")
        return result


@dataclass
class TensorTableEntry:
    """One enqueued tensor awaiting its collective.
    Parity: common.h TensorTableEntry."""

    name: str
    array: np.ndarray
    handle: int
    request: Request
    root_rank: int = -1
    splits: Optional[List[int]] = None
    enqueue_ns: int = field(default_factory=time.monotonic_ns)


class _MessageTable:
    """Coordinator-side ready-count tracking.
    Parity: controller.h:33 MessageTable + IncrementTensorCount
    (controller.cc:787-810)."""

    def __init__(self, size: int):
        self.size = size
        self.entries: Dict[str, List[Request]] = {}
        self.first_seen: Dict[str, float] = {}

    @staticmethod
    def key_of(req: Request) -> str:
        """Table key: process-set requests are scoped by set id, so the
        same tensor name may be in flight in two different sets at once
        (both subgroups allreducing "grad.w" is legitimate traffic)."""
        if req.process_set_id:
            return f"{req.tensor_name}@ps{req.process_set_id}"
        return req.tensor_name

    def increment(self, req: Request, joined_size: int) -> bool:
        """Record a rank's readiness; True when all non-joined ranks are
        in (for a process-set request: when every member is in — join is
        global-set-only, so joined_size does not apply)."""
        key = self.key_of(req)
        lst = self.entries.setdefault(key, [])
        if any(q.request_rank == req.request_rank for q in lst):
            # Duplicate ready tick from the same rank: a child re-sends
            # its in-flight request frames after re-parenting away from
            # a dead sub-coordinator, and the original may have been
            # relayed just before the parent died.  Counting it twice
            # would fire the collective before every rank is in.
            return False
        lst.append(req)
        self.first_seen.setdefault(key, time.monotonic())
        if req.process_set_id:
            return len(lst) == req.process_set_size
        return len(lst) == self.size - joined_size

    def pop(self, name: str) -> List[Request]:
        self.first_seen.pop(name, None)
        return self.entries.pop(name)


def _np_dtype(dt: DataType):
    name = dtype_to_numpy_name(dt)
    if name in ("bfloat16", "float8_e4m3fn", "float8_e5m2"):
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))
    return np.dtype(name)


class _EngineBase:
    """Shared enqueue-side logic and introspection."""

    def __init__(self, rank, size, local_rank, local_size,
                 cross_rank, cross_size):
        self.rank = rank
        self.size = size
        self.local_rank = local_rank
        self.local_size = local_size
        self.cross_rank = cross_rank
        self.cross_size = cross_size
        self.is_homogeneous = True
        self.handles = HandleManager()
        self._pending_names: set = set()
        self._name_lock = threading.Lock()
        self._barrier_counters = {0: 0}  # per process-set id

    # -- duplicate-name guard (parity: tensor_queue.cc:27-35) -------------

    def _claim_name(self, name: str) -> None:
        with self._name_lock:
            if name in self._pending_names:
                raise ValueError(
                    f"Requested a collective on a tensor with the same name "
                    f"as another tensor that is currently being processed: "
                    f"{name}")
            self._pending_names.add(name)

    def _release_name(self, name: str) -> None:
        with self._name_lock:
            self._pending_names.discard(name)

    def poll(self, handle: int) -> bool:
        return self.handles.poll(handle)

    def synchronize(self, handle: int, timeout: Optional[float] = None):
        return self.handles.wait(handle, timeout)

    def cache_stats(self) -> Dict[str, int]:
        return {"hits": 0, "misses": 0, "evictions": 0, "size": 0,
                "capacity": 0}


class SingleProcessEngine(_EngineBase):
    """size == 1: every collective is the identity (modulo scaling), applied
    synchronously.  Keeps the async handle API so user code is unchanged."""

    def __init__(self):
        super().__init__(0, 1, 0, 1, 0, 1)
        self.timeline = timeline_mod.from_env(0)
        _telemetry.init_from_env(0, 0)
        self._tracer = None  # tracing needs a gang; see PyEngine
        # Serving surface (serving/loop.py): a broadcast to a gang of
        # one is a local enqueue, so the loop's drive/apply split works
        # unchanged single-process.
        self.epoch = 0
        self._aborted = False
        self._serve_inbox: List[bytes] = []
        self._serve_cv = threading.Condition()
        self._shutdown_requested = threading.Event()
        self._shutdown_flag = threading.Event()

    def shutdown(self):
        self._shutdown_flag.set()
        with self._serve_cv:
            self._serve_cv.notify_all()
        self.timeline.shutdown()

    def serve_broadcast(self, payload: bytes) -> None:
        with self._serve_cv:
            self._serve_inbox.append(payload)
            self._serve_cv.notify_all()

    def serve_recv(self, timeout: float) -> Optional[bytes]:
        deadline = time.monotonic() + timeout
        with self._serve_cv:
            while True:
                if self._serve_inbox:
                    return self._serve_inbox.pop(0)
                if self._shutdown_flag.is_set() \
                        or self._shutdown_requested.is_set():
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._serve_cv.wait(min(0.05, remaining))

    def _finish(self, name, op_name, result):
        self.timeline.negotiate_start(name, op_name)
        self.timeline.negotiate_rank_ready(name, 0)
        self.timeline.negotiate_end(name)
        self.timeline.start(name, op_name)
        self.timeline.end(name)
        h = self.handles.allocate()
        self.handles.mark_done(h, Status.ok(), result)
        return h

    def _check_ps(self, process_set):
        # size 1: the only valid set is {0} (shared validation helper).
        if process_set is not None:
            process_set.validate(0, 1)

    def allreduce_async(self, name, array, op=ReduceOp.SUM,
                        prescale=1.0, postscale=1.0, process_set=None):
        self._check_ps(process_set)
        out = np.asarray(array)
        if prescale != 1.0 or postscale != 1.0:
            out = out * (prescale * postscale)
        else:
            out = out.copy()
        return self._finish(name, "ALLREDUCE", out)

    def allgather_async(self, name, array, process_set=None):
        self._check_ps(process_set)
        return self._finish(name, "ALLGATHER", np.asarray(array).copy())

    def reducescatter_async(self, name, array, op=ReduceOp.SUM,
                            process_set=None):
        # size 1: the reduction of one rank's tensor, scattered to the
        # one rank — the input itself.
        self._check_ps(process_set)
        return self._finish(name, "REDUCESCATTER",
                            np.asarray(array).copy())

    def broadcast_async(self, name, array, root_rank=0, process_set=None):
        self._check_ps(process_set)
        if root_rank != 0:
            raise ValueError(
                f"broadcast root rank {root_rank} out of range for size 1")
        return self._finish(name, "BROADCAST", np.asarray(array).copy())

    def alltoall_async(self, name, array, splits=None, process_set=None):
        # Same splits validation as the multi-process engines, so code
        # written single-process fails the same way it would at scale.
        self._check_ps(process_set)
        arr = np.asarray(array)
        if splits is not None:
            splits = [int(s) for s in splits]
            if len(splits) != 1:
                raise ValueError(
                    "alltoall needs one split per participant (1)")
            if sum(splits) != (arr.shape[0] if arr.ndim else 0):
                raise ValueError("splits must sum to dim 0")
        # (no-splits divisibility: any dim 0 divides a world of 1)
        return self._finish(name, "ALLTOALL", arr.copy())

    def barrier(self, process_set=None):
        self._check_ps(process_set)
        return None

    def join(self) -> int:
        return 0


class PyEngine(_EngineBase):
    """Multi-process engine: background thread, star controller, ring data
    plane.  See module docstring for the parity map."""

    def __init__(self, rank, size, local_rank, local_size,
                 cross_rank, cross_size, rdv_addr, rdv_port):
        super().__init__(rank, size, local_rank, local_size,
                         cross_rank, cross_size)
        self.log = get_logger(rank)
        self.timeline = timeline_mod.from_env(rank)
        self.cycle_time = env_util.cycle_time_ms() / 1e3
        self.fusion_threshold = env_util.fusion_threshold_bytes()
        # Ring-hop receive segmentation (docs/performance.md); autotunable
        # like the fusion threshold, receiver-local so any mix of segment
        # settings (and the native engine) stays wire-compatible.
        self.ring_segment_bytes = env_util.ring_segment_bytes()
        self.stall_warn_s = env_util.get_float(env_util.STALL_CHECK_TIME, 60.0)
        self.stall_shutdown_s = env_util.get_float(
            env_util.STALL_SHUTDOWN_TIME, 0.0)
        self.stall_check_disable = env_util.get_bool(
            env_util.STALL_CHECK_DISABLE, False)
        # Two-level data plane (parity: HOROVOD_HIERARCHICAL_* knobs and
        # NCCLHierarchicalAllreduce / MPIHierarchicalAllgather).  Only
        # effective on a genuinely hierarchical topology — see
        # hierarchical_topology_ok().
        self.hierarchical_allreduce = env_util.get_bool(
            env_util.HIERARCHICAL_ALLREDUCE, False)
        self.hierarchical_allgather = env_util.get_bool(
            env_util.HIERARCHICAL_ALLGATHER, False)
        self.native_fallback_reason = None
        # Elastic membership epoch (horovod_tpu.elastic): stamped on every
        # list frame; frames from another incarnation are dropped (worker)
        # or rejected (coordinator) so a zombie rank from a previous gang
        # cannot corrupt this one's negotiation.
        self.epoch = env_util.get_int(env_util.ELASTIC_EPOCH, 0)

        # Telemetry (horovod_tpu.telemetry; docs/metrics.md).  The
        # registry hooks are zero-cost when off, but call sites whose
        # arguments allocate guard on this flag.  The straggler detector
        # is coordinator-only: it folds the per-rank ready ticks the
        # coordinator already sees into a skew histogram.
        self._metrics_on = _telemetry.init_from_env(rank, local_rank,
                                                    size=size)
        self._straggler = None
        if self._metrics_on:
            _tmx.set_gauge("hvd_elastic_epoch", self.epoch)
            if rank == 0:
                self._straggler = _telemetry.StragglerDetector(
                    env_util.get_float(env_util.STRAGGLER_WARN_MS, 0.0),
                    size)

        # Gang-wide tracing (telemetry/trace.py; docs/timeline.md "Gang-
        # wide tracing").  Unlike the rank-0 timeline, EVERY rank traces;
        # None when HVD_TRACE is unset, and all hot-path hooks are one
        # attribute load + None check.
        self._tracer = trace_mod.from_env(rank)
        self._clock_sync_cycles = env_util.trace_clock_sync_cycles()
        self._clock_ping_countdown = 0  # 0 = ping on the next cycle
        if self._tracer is not None and rank == 0:
            # The coordinator defines the gang clock axis: offset 0.
            self._tracer.clock(0, 0)

        # Always-on flight recorder (telemetry/blackbox.py;
        # docs/fault_tolerance.md "the black box").  Process-global so
        # the ring survives elastic engine teardown; every terminal
        # failure path below calls dump() before raising/propagating.
        self._blackbox = blackbox_mod.from_env(rank, epoch=self.epoch)
        self._blackbox_seq = 0
        if self._blackbox is not None:
            self._blackbox.note("engine.init", 0,
                                {"rank": rank, "size": size,
                                 "epoch": self.epoch})

        # request queue (tensor queue) + tensor table
        self._queue_lock = threading.Lock()
        self._request_queue: List[Request] = []
        self._table: Dict[str, TensorTableEntry] = {}

        # join state
        self._joined = False
        self._join_handle: Optional[int] = None
        self._last_joined_rank = -1

        # shutdown: `_shutdown_requested` asks the loop to negotiate the
        # stop through the controller (shutdown bits on the wire) so all
        # ranks exit in the same cycle; `_shutdown_flag` is the hard
        # local stop; `_loop_exited` lets shutdown() bound its wait.
        self._shutdown_requested = threading.Event()
        self._shutdown_flag = threading.Event()
        self._loop_exited = threading.Event()
        self._closed = False  # shutdown() ran its cleanup (socket close)
        self._aborted = False
        self._abort_reason = None
        self._abort_exc = None  # typed abort (e.g. FencedError)

        # coordinator state
        self._msg_table = _MessageTable(size) if rank == 0 else None
        self._joined_ranks: set = set()
        self._ctrl_inbox: "list" = []
        self._ctrl_lock = threading.Lock()
        self._last_stall_check = time.monotonic()

        # Hierarchical control tree (docs/fault_tolerance.md
        # "Hierarchical control plane, fencing, and quorum").  Planned
        # from the block topology BEFORE bootstrap: on a multi-host gang
        # the lowest local rank of each non-root host becomes a
        # sub-coordinator that folds its children's request/heartbeat
        # frames into one TAG_TREE_UP aggregate, so root-side recv work
        # is O(hosts), not O(ranks).  Single-host gangs plan an empty
        # tree and stay byte-identical to the seed star (pinned by
        # tests/test_ctrl_tree.py).
        self.ctrl_fanout = env_util.ctrl_fanout()
        self._tree_parent, self._tree_children, self._rank_route = \
            self._plan_tree()
        self._tree_parent_sock = None          # child: link to sub-coord
        self._tree_child_socks: Dict[int, socket.socket] = {}  # sub-coord
        self._tree_up_buf: List[tuple] = []    # sub-coord: pending entries
        self._tree_up_lock = threading.Lock()
        self._tree_orphaned = False            # child: sub-coord died
        # Child: request payloads sent up the tree since the last
        # response frame — re-sent after a re-parent because the dead
        # sub-coordinator may not have relayed them (bounded; the
        # coordinator absorbs duplicates idempotently).
        self._tree_unacked: List[bytes] = []
        self._reparented_ranks: set = set()    # root: adopted orphans
        self._fenced: Optional[tuple] = None   # worker: TAG_FENCE payload

        # Liveness (parity-extension): heartbeats piggyback on the ctrl
        # connections; a worker silent past the timeout is evicted via
        # the Join machinery.  Default OFF (timeout 0) — identical wire
        # traffic to the pre-heartbeat protocol, and safe to mix with
        # the native engine, which never sees the new frame tag.
        self.heartbeat_timeout = env_util.get_float(
            env_util.HEARTBEAT_TIMEOUT,
            env_util.get_float("HOROVOD_HEARTBEAT_TIMEOUT", 0.0))
        self.heartbeat_interval = env_util.get_float(
            env_util.HEARTBEAT_INTERVAL,
            max(0.05, self.heartbeat_timeout / 4.0))
        self._evicted_ranks: set = set()      # dead ranks, every rank
        self._ranks_failed: List[int] = []    # raises on next enqueue
        self._conn_lost: set = set()          # recv threads -> coord cycle
        self._ctrl_conn_lost = False          # worker: coordinator EOF
        self._last_seen: Dict[int, float] = {}
        self._last_send = time.monotonic()

        # Collective deadlines (docs/fault_tolerance.md "hung ranks vs
        # dead ranks").  Default OFF (0) — identical hot path to the
        # seed, pinned by tests/test_timeouts.py.  When on, every eager
        # collective carries a deadline; a local hop timeout triggers
        # the gang-wide abort agreement over the still-live control
        # mesh (TAG_ABORT_REPORT / TAG_PROBE / TAG_PROBE_ACK /
        # TAG_ABORT_VERDICT).
        self.collective_timeout = env_util.collective_timeout_s()
        self.collective_probe_timeout = env_util.get_float(
            env_util.COLLECTIVE_PROBE_TIMEOUT,
            max(0.5, self.collective_timeout / 2.0))
        # Ctrl sends can happen off the background thread on both sides:
        # workers send from _worker_cycle AND the recv thread (probe
        # acks); the coordinator sends from the background thread AND
        # the serving loop's thread (TAG_SERVE admission broadcasts).
        # Serialize so frames never interleave.
        self._ctrl_send_lock = threading.Lock()
        # Serving admission broadcast (TAG_SERVE): frames land here on
        # every rank (the coordinator delivers to itself directly) and
        # the serving loop drains them via serve_recv().
        self._serve_inbox: List[bytes] = []
        self._serve_cv = threading.Condition()
        # Coordinator: reports/acks captured by the ctrl recv threads.
        self._abort_inbox: List[tuple] = []
        self._abort_lock = threading.Lock()
        # Worker: verdict handoff from the recv thread to the blocked
        # background thread.
        self._abort_verdict: Optional[tuple] = None
        self._abort_cv = threading.Condition(self._abort_lock)
        # Busy marker for probe acks: monotonic start of the collective
        # currently executing on the background thread (0.0 = idle).
        # Only maintained when the deadline knob is on.
        self._in_collective_since = 0.0
        self._in_collective_name = ""
        # Coordinator: last ruled verdict, re-sent to stragglers whose
        # own hop deadline fires after the broadcast.
        self._last_verdict: Optional[tuple] = None
        # Coordinator: flight-recorder dumps pulled from live workers
        # after an abort verdict (TAG_BLACKBOX_DUMP frames, captured by
        # the ctrl recv threads).
        self._blackbox_inbox: List[tuple] = []
        self._blackbox_lock = threading.Lock()

        # response cache (parity: response_cache.cc; protocol adapted to
        # the star controller — see common/response_cache.py docstring).
        # All cache state is touched only on the background thread.
        self._cache = rcache.ResponseCache(
            env_util.get_int(env_util.CACHE_CAPACITY, 1024))
        self._cache_classify_enabled = True
        self._resend_uncached: set = set()
        self._hit_ranks: Dict[str, set] = {}

        # autotuner (coordinator only; parity: parameter_manager.cc —
        # rank 0 tunes and broadcasts).
        self._pm = None
        if rank == 0:
            from horovod_tpu.autotune import ParameterManager

            self._pm = ParameterManager.from_env(
                self.fusion_threshold, self.cycle_time,
                self.hierarchical_allreduce, self.hierarchical_allgather,
                hierarchical_ok=self.hierarchical_topology_ok(),
                ring_segment_bytes=self.ring_segment_bytes)
        self._pending_params = None

        self._bootstrap(rdv_addr, rdv_port)

        if self.epoch and self.timeline.enabled:
            self.timeline.elastic_event(f"ELASTIC_EPOCH_{self.epoch}",
                                        size=self.size)

        self._bg = threading.Thread(
            target=self._background_loop, name="hvd-background", daemon=True)
        self._bg.start()

    # ------------------------------------------------------------------
    # hierarchical control tree
    # ------------------------------------------------------------------

    def _plan_tree(self):
        """Plan the two-level control tree from the block topology.

        Returns ``(parent, children, route)``:

        * ``parent``: this rank's sub-coordinator (None = talk to the
          root directly — the root itself, sub-coordinators, the root's
          own host, and fan-out overflow),
        * ``children``: ranks this sub-coordinator folds,
        * ``route``: root-only map child rank -> sub-coordinator rank.

        Empty on a single-host gang (``cross_size == 1``) or a
        non-block rank layout, where the flat star is already O(hosts):
        the seed protocol runs byte-identical.
        """
        none = (None, [], {})
        if self.size <= 1 or self.local_size <= 1 or self.cross_size <= 1:
            return none
        if not env_util.ctrl_tree_on():
            return none
        if not self.hierarchical_topology_ok():
            return none
        fanout = self.ctrl_fanout
        parent, children, route = None, [], {}
        ls = self.local_size
        for host in range(1, self.cross_size):
            sub = host * ls
            if sub >= self.size:
                break
            members = range(sub + 1, min((host + 1) * ls, self.size))
            folded = list(members if fanout <= 0 else
                          list(members)[:fanout])
            for c in folded:
                route[c] = sub
                if c == self.rank:
                    parent = sub
            if self.rank == sub:
                children = folded
        return parent, children, route

    # ------------------------------------------------------------------
    # bootstrap: rendezvous + socket meshes
    # ------------------------------------------------------------------

    def _bootstrap(self, rdv_addr: str, rdv_port: int) -> None:
        from horovod_tpu.bootstrap import bootstrap_mesh

        # Recovery-ladder mode (HVD_WIRE_CRC=1, docs/fault_tolerance.md
        # "recovery ladder"): keep the bootstrap listener open so a
        # dropped data socket can be re-dialed mid-gang, and remember
        # every peer's advertised address for the re-dial.
        ladder_on = env_util.wire_crc()
        self._reconnect_listener = None
        tree = {"parent": self._tree_parent, "children": self._tree_children}
        if ladder_on:
            (self._data, self._ctrl_sock, self._ctrl_socks,
             kv, kv_prefix, mesh_peers, mesh_listener) = bootstrap_mesh(
                self.rank, self.size, rdv_addr, rdv_port,
                shm_capable=True, keep_listener=True, tree=tree)
        else:
            (self._data, self._ctrl_sock, self._ctrl_socks,
             kv, kv_prefix) = bootstrap_mesh(
                self.rank, self.size, rdv_addr, rdv_port, shm_capable=True,
                tree=tree)
        self._tree_parent_sock = tree.get("parent_sock")
        self._tree_child_socks = tree.get("child_socks") or {}

        # Data-plane hot-path state (docs/performance.md): one transport
        # per peer, selected at mesh-build time (shm ring for same-host
        # peers unless HVD_SHM_DISABLE, TCP otherwise), each with one
        # persistent sender thread — ring hops enqueue sends instead of
        # spawning a thread per hop — plus the persistent fusion/hop
        # scratch the collectives pack into.  Torn down in shutdown();
        # an elastic re-form goes through shutdown() + a fresh engine
        # under a new rendezvous scope, so re-bootstrap always starts
        # from an empty pool and fresh pairing keys.
        from horovod_tpu.ops.fusion_buffer import FusionBuffer
        from horovod_tpu.utils import transport as tpt

        if ladder_on:
            from horovod_tpu.utils import ladder

            self._transports, self._reconnect_listener = \
                ladder.build_ladder_links(
                    self.rank, self.size, self._data, kv, kv_prefix,
                    mesh_peers, mesh_listener, epoch=self.epoch)
            # Ladder links own their sender threads (no PeerSenders).
            self._senders = {}
        else:
            self._transports = tpt.build_transports(
                self.rank, self.size, self._data, kv, kv_prefix)
            # TCP transports own the engine's PeerSenders; shm peers
            # have no socket sender (their thread lives inside the
            # transport), so the per-peer sender-thread count stays
            # exactly one either way.
            self._senders = {r: t.sender
                             for r, t in self._transports.items()
                             if t.kind == "tcp"}
        self._fusion_buf = FusionBuffer()

        # ctrl receiver threads
        if self.rank == 0:
            now = time.monotonic()
            self._last_seen = {r: now for r in self._ctrl_socks}
            for r, s in self._ctrl_socks.items():
                threading.Thread(target=self._ctrl_recv_loop,
                                 args=(r, s), daemon=True).start()
        else:
            threading.Thread(target=self._worker_recv_loop, daemon=True
                             ).start()
            if self._tree_parent_sock is not None:
                threading.Thread(target=self._tree_parent_recv_loop,
                                 daemon=True).start()
            for r, s in self._tree_child_socks.items():
                threading.Thread(target=self._tree_child_recv_loop,
                                 args=(r, s), daemon=True).start()
        self._response_inbox: List[bytes] = []
        self._response_lock = threading.Lock()
        self._response_cv = threading.Condition(self._response_lock)

    def _ctrl_recv_loop(self, peer_rank: int, sock: socket.socket) -> None:
        try:
            while not self._shutdown_flag.is_set():
                tag, payload = su.recv_frame(sock)
                self._dispatch_ctrl_frame(peer_rank, tag, payload, sock)
        except (ConnectionError, OSError):
            # EOF/reset: fast liveness signal, stronger than a missed
            # heartbeat (only acted on when heartbeats are enabled).
            self._conn_lost.add(peer_rank)

    def _dispatch_ctrl_frame(self, peer_rank: int, tag: int,
                             payload: bytes, sock) -> None:
        """Coordinator-side dispatch of one control frame — from a
        rank's own socket, or replayed from a TAG_TREE_UP aggregate
        (then ``peer_rank`` is the entry's origin, and ``sock`` the
        sub-coordinator's link)."""
        # Any frame is proof of life; TAG_HEARTBEAT carries nothing else.
        self._last_seen[peer_rank] = time.monotonic()
        if tag == su.TAG_REQUEST_LIST:
            with self._ctrl_lock:
                self._ctrl_inbox.append((peer_rank, payload))
        elif tag == su.TAG_TREE_UP:
            # A sub-coordinator's aggregate: dispatch every folded entry
            # as if it had arrived on its origin rank's own socket.
            entries, epoch = wire.decode_tree_up(payload)
            for origin, etag, epayload in entries:
                self._dispatch_ctrl_frame(origin, etag, epayload, sock)
        elif tag == su.TAG_REPARENT:
            rank, old_parent, epoch = wire.decode_reparent(payload)
            self._note_reparent(peer_rank, old_parent, epoch)
        elif tag in (su.TAG_ABORT_REPORT, su.TAG_PROBE_ACK):
            with self._abort_lock:
                self._abort_inbox.append(
                    (peer_rank, tag, payload))
        elif tag == su.TAG_CLOCK_PING:
            # Trace clock sync (telemetry/trace.py): echo the
            # worker's t0 with our monotonic read.  Answered
            # from THIS thread so the estimate never waits on a
            # busy background cycle (cf. TAG_PROBE).
            t0_ns, pepoch = wire.decode_clock_ping(payload)
            pong = wire.encode_clock_pong(
                t0_ns, time.monotonic_ns(), pepoch)
            try:
                with self._ctrl_send_lock:
                    su.send_frame(sock, su.TAG_CLOCK_PONG, pong)
            except (ConnectionError, OSError):
                pass  # liveness machinery owns the eviction
        elif tag == su.TAG_BLACKBOX_DUMP:
            # A worker's flight-recorder ring, answering our
            # post-verdict pull (_pull_blackbox_dumps).
            with self._blackbox_lock:
                self._blackbox_inbox.append((peer_rank, payload))

    def _note_reparent(self, rank: int, old_parent: int,
                       epoch: int) -> None:
        """Root: a child of a dead sub-coordinator adopted itself back
        to the direct star.  Only the dead parent gets evicted — the
        orphan keeps its seat, and its in-flight collectives ride on."""
        self._reparented_ranks.add(rank)
        self._rank_route.pop(rank, None)
        self.log.warning(
            "rank %d re-parented to the root (sub-coordinator %d died)",
            rank, old_parent)
        _tmx.inc_counter("hvd_subcoord_reparents_total")
        blackbox_mod.note("subcoord.reparent", time.monotonic_ns(),
                          rank=rank, old_parent=old_parent, epoch=epoch)
        if self.timeline.enabled:
            self.timeline.instant(timeline_mod.SUBCOORD_REPARENT,
                                  rank=rank, old_parent=old_parent)

    def _worker_recv_loop(self) -> None:
        try:
            while not self._shutdown_flag.is_set():
                tag, payload = su.recv_frame(self._ctrl_sock)
                self._dispatch_worker_frame(tag, payload)
        except (ConnectionError, OSError):
            # Coordinator EOF/reset.  During a negotiated shutdown (or
            # after our own close) this is expected teardown noise;
            # otherwise it is the fastest dead-hub signal the worker
            # has — the next worker cycle drains any already-received
            # shutdown frame and only then declares the hub lost.
            if not (self._shutdown_flag.is_set()
                    or self._shutdown_requested.is_set()
                    or self._closed):
                self._ctrl_conn_lost = True
                # Wake a serving loop parked in serve_recv: the abort
                # it needs fires from the next worker cycle, but the
                # cycle only runs every cycle_time — notify so nothing
                # sleeps a full timeout on a dead hub.
                with self._serve_cv:
                    self._serve_cv.notify_all()

    def _dispatch_worker_frame(self, tag: int, payload: bytes) -> None:
        """Worker-side dispatch of one coordinator frame — from the
        direct control socket, or forwarded down the tree by this
        rank's sub-coordinator.  Replies (probe acks, blackbox dumps)
        always go up the DIRECT socket: it stays live even while the
        sub-coordinator is dying, which is exactly when the coordinator
        needs them."""
        if tag == su.TAG_TREE_DOWN:
            # Sub-coordinator: route a root frame to one child or fan
            # it out to the whole host.
            target, itag, ipayload = wire.decode_tree_down(payload)
            for r, s in list(self._tree_child_socks.items()):
                if target != -1 and r != target:
                    continue
                try:
                    _fi.fire("ctrl.subcoord.send", str(r))
                    with self._ctrl_send_lock:
                        su.send_frame(s, itag, ipayload)
                except (ConnectionError, OSError):
                    pass  # the root's liveness machinery owns eviction
            return
        if tag == su.TAG_FENCE:
            # Typed rejection: the coordinator is at a newer membership
            # epoch and we have no seat in it.  The next worker cycle
            # raises FencedError to the training loop and exits.
            self._fenced = wire.decode_fence(payload)
            with self._serve_cv:
                self._serve_cv.notify_all()
            return
        if tag == su.TAG_RESPONSE_LIST:
            with self._response_cv:
                self._response_inbox.append(payload)
                self._response_cv.notify_all()
        elif tag == su.TAG_PROBE:
            # Answer from THIS thread: the background thread may
            # be the very thing that is wedged in the data plane.
            since = self._in_collective_since
            busy_s = (time.monotonic() - since) if since else 0.0
            ack = wire.encode_probe_ack(
                since > 0.0, busy_s, self.epoch)
            try:
                with self._ctrl_send_lock:
                    su.send_frame(self._ctrl_sock,
                                  su.TAG_PROBE_ACK, ack)
            except (ConnectionError, OSError):
                pass
        elif tag == su.TAG_ABORT_VERDICT:
            vname, vranks, vepoch = wire.decode_abort_verdict(
                payload)
            if vepoch != self.epoch:
                return
            with self._abort_cv:
                self._abort_verdict = (vname, vranks)
                self._abort_cv.notify_all()
        elif tag == su.TAG_SERVE:
            with self._serve_cv:
                self._serve_inbox.append(payload)
                self._serve_cv.notify_all()
        elif tag == su.TAG_CLOCK_PONG:
            # Midpoint method: offset maps this rank's monotonic
            # axis onto rank 0's (add offset to local times).
            t1_ns = time.monotonic_ns()
            t0_ns, tc_ns, pepoch = wire.decode_clock_pong(payload)
            tr = self._tracer
            if tr is not None and pepoch == self.epoch:
                offset_ns = tc_ns - (t0_ns + t1_ns) // 2
                tr.clock(offset_ns, t1_ns - t0_ns)
                # The flight recorder rides the same estimate;
                # its dump ships the freshest value so the
                # postmortem can align rank timelines.
                blackbox_mod.note_clock_offset(offset_ns)
                if self._metrics_on:
                    _tmx.set_gauge("hvd_trace_clock_skew_seconds",
                                   offset_ns / 1e9)
        elif tag == su.TAG_BLACKBOX:
            # Coordinator pulling our flight-recorder ring after
            # an abort verdict.  Answered from THIS thread — the
            # background thread may be the wedged party, and its
            # evidence is exactly what the pull is for.
            bb = blackbox_mod.get()
            if bb is not None:
                blob = bb.dump_bytes("coordinator_pull")
                reply = wire.encode_blackbox_dump(
                    self.rank, self.epoch, blob)
                try:
                    with self._ctrl_send_lock:
                        su.send_frame(self._ctrl_sock,
                                      su.TAG_BLACKBOX_DUMP, reply)
                except (ConnectionError, OSError):
                    pass

    # -- hierarchical control tree (docs/fault_tolerance.md) -------------
    #
    # Children of a per-host sub-coordinator send their request/heartbeat
    # frames over a dedicated chan-2 bootstrap link; the sub-coordinator
    # folds everything it buffered plus its own frame into ONE
    # TAG_TREE_UP on its direct root socket each cycle, so the root's
    # recv work scales with hosts, not ranks.  Responses always ride the
    # direct star — a response lost inside a dying sub-coordinator would
    # desync the gang, so nothing irreplaceable ever transits the tree.

    def _tree_parent_recv_loop(self) -> None:
        """Child: frames forwarded down by our sub-coordinator (routed
        probes).  EOF here is the re-parent trigger: the direct root
        socket is still live, so adopt ourselves back to the star."""
        sock = self._tree_parent_sock
        try:
            while not self._shutdown_flag.is_set():
                tag, payload = su.recv_frame(sock)
                self._dispatch_worker_frame(tag, payload)
        except (ConnectionError, OSError):
            if not (self._shutdown_flag.is_set()
                    or self._shutdown_requested.is_set()
                    or self._closed):
                self._reparent_to_root()

    def _tree_child_recv_loop(self, child: int,
                              sock: socket.socket) -> None:
        """Sub-coordinator: buffer a child's uplink frames; the next
        worker cycle folds them into one TAG_TREE_UP.  EOF means the
        child died — the root's heartbeat timeout owns that eviction, so
        nothing to do here."""
        try:
            while not self._shutdown_flag.is_set():
                tag, payload = su.recv_frame(sock)
                with self._tree_up_lock:
                    self._tree_up_buf.append((child, tag, payload))
        except (ConnectionError, OSError):
            pass

    def _reparent_to_root(self) -> None:
        """Child of a dead sub-coordinator: announce TAG_REPARENT on the
        still-open direct socket and resend the recent request payloads
        that may have died inside the parent (the coordinator's message
        table is idempotent per rank, so duplicates are harmless).  From
        here on this rank speaks the flat star; only the dead parent is
        evicted — no gang-wide abort."""
        if self._tree_orphaned or self._tree_parent is None:
            return
        self._tree_orphaned = True
        old = self._tree_parent
        self.log.warning(
            "sub-coordinator %d unreachable; re-parenting to the root",
            old)
        try:
            _fi.fire("ctrl.reparent", str(self.rank))
            with self._ctrl_send_lock:
                su.send_frame(self._ctrl_sock, su.TAG_REPARENT,
                              wire.encode_reparent(self.rank, old,
                                                   self.epoch))
                for payload in list(self._tree_unacked):
                    su.send_frame(self._ctrl_sock, su.TAG_REQUEST_LIST,
                                  payload)
            self._last_send = time.monotonic()
            _tmx.inc_counter("hvd_subcoord_reparents_total")
            blackbox_mod.note("subcoord.reparent", time.monotonic_ns(),
                              rank=self.rank, old_parent=old,
                              epoch=self.epoch)
        except (ConnectionError, OSError):
            # The direct socket is gone too — that is a dead hub, and
            # the ordinary lost-coordinator abort owns it.
            self._ctrl_conn_lost = True
            with self._serve_cv:
                self._serve_cv.notify_all()

    # -- serving admission broadcast (docs/serving.md) -------------------

    def serve_broadcast(self, payload: bytes) -> None:
        """Coordinator: push one serve-step frame (wire.py ServeDelta) to
        every live worker and to the local inbox.  Called from the
        serving loop's thread, hence the ctrl send lock."""
        if self.rank != 0:
            raise RuntimeError("serve_broadcast is coordinator-only")
        for r, s in self._ctrl_socks.items():
            if r in self._evicted_ranks:
                continue
            try:
                with self._ctrl_send_lock:
                    su.send_frame(s, su.TAG_SERVE, payload)
            except (ConnectionError, OSError):
                pass  # liveness machinery owns the eviction
        with self._serve_cv:
            self._serve_inbox.append(payload)
            self._serve_cv.notify_all()

    def serve_recv(self, timeout: float) -> Optional[bytes]:
        """Block (≤ ``timeout`` s) for the next serve-step frame.  None
        on timeout or local shutdown; raises RanksFailedError once peers
        have been declared failed so the serving loop re-forms through
        the same path as a failed collective."""
        deadline = time.monotonic() + timeout
        with self._serve_cv:
            while True:
                if self._serve_inbox:
                    return self._serve_inbox.pop(0)
                if self._ranks_failed:
                    raise RanksFailedError(self._ranks_failed)
                if self._aborted or self._shutdown_flag.is_set() \
                        or self._shutdown_requested.is_set():
                    return None
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                # Short slices: the cv is only notified on frame arrival,
                # and abort/shutdown must still wake this thread.
                self._serve_cv.wait(min(0.05, remaining))

    # ------------------------------------------------------------------
    # enqueue API (framework-thread side)
    # ------------------------------------------------------------------

    def _enqueue(self, entry: TensorTableEntry) -> int:
        if self._ranks_failed:
            # In-flight ops already completed on the survivors; the next
            # submission is the point where the training loop can react.
            raise RanksFailedError(self._ranks_failed)
        if self._abort_exc is not None:
            # Typed abort (FencedError, ...): the class IS the signal —
            # the elastic wrapper re-forms on RanksFailedError but must
            # let a fenced zombie exit.
            raise self._abort_exc
        if self._aborted or self._shutdown_flag.is_set() \
                or self._shutdown_requested.is_set():
            raise RuntimeError("horovod_tpu runtime has been shut down")
        self._claim_name(entry.name)
        with self._queue_lock:
            self._table[entry.name] = entry
            self._request_queue.append(entry.request)
        return entry.handle

    def _ps_fields(self, process_set):
        """Validate + unpack a ProcessSet into (id, size) request fields."""
        if process_set is None:
            return 0, 0
        return process_set.validate(self.rank, self.size)

    def allreduce_async(self, name, array, op=ReduceOp.SUM,
                        prescale=1.0, postscale=1.0, process_set=None):
        arr = np.ascontiguousarray(array)
        ps_id, ps_size = self._ps_fields(process_set)
        req = Request(
            request_rank=self.rank,
            request_type=RequestType.ALLREDUCE,
            tensor_type=dtype_from_numpy(arr.dtype),
            tensor_name=name,
            device="cpu",
            tensor_shape=TensorShape(arr.shape),
            reduce_op=op,
            prescale_factor=prescale,
            postscale_factor=postscale,
            process_set_id=ps_id,
            process_set_size=ps_size,
        )
        h = self.handles.allocate()
        return self._enqueue(TensorTableEntry(name, arr, h, req))

    def allgather_async(self, name, array, process_set=None):
        arr = np.ascontiguousarray(array)
        ps_id, ps_size = self._ps_fields(process_set)
        req = Request(
            request_rank=self.rank,
            request_type=RequestType.ALLGATHER,
            tensor_type=dtype_from_numpy(arr.dtype),
            tensor_name=name,
            device="cpu",
            tensor_shape=TensorShape(arr.shape),
            process_set_id=ps_id,
            process_set_size=ps_size,
        )
        h = self.handles.allocate()
        return self._enqueue(TensorTableEntry(name, arr, h, req))

    def reducescatter_async(self, name, array, op=ReduceOp.SUM,
                            process_set=None):
        arr = np.ascontiguousarray(array)
        if arr.ndim == 0:
            raise ValueError(
                "reducescatter needs at least one dimension to scatter "
                "over (got a scalar)")
        ps_id, ps_size = self._ps_fields(process_set)
        req = Request(
            request_rank=self.rank,
            request_type=RequestType.REDUCESCATTER,
            tensor_type=dtype_from_numpy(arr.dtype),
            tensor_name=name,
            device="cpu",
            tensor_shape=TensorShape(arr.shape),
            reduce_op=op,
            process_set_id=ps_id,
            process_set_size=ps_size,
        )
        h = self.handles.allocate()
        return self._enqueue(TensorTableEntry(name, arr, h, req))

    def broadcast_async(self, name, array, root_rank=0, process_set=None):
        arr = np.ascontiguousarray(array)
        if not (0 <= root_rank < self.size):
            raise ValueError(
                f"broadcast root rank {root_rank} out of range "
                f"[0, {self.size})")
        ps_id, ps_size = self._ps_fields(process_set)
        if process_set is not None and \
                root_rank not in process_set.ranks:
            raise ValueError(
                f"broadcast root rank {root_rank} (global) is not a "
                f"member of {process_set}")
        req = Request(
            request_rank=self.rank,
            request_type=RequestType.BROADCAST,
            tensor_type=dtype_from_numpy(arr.dtype),
            tensor_name=name,
            device="cpu",
            tensor_shape=TensorShape(arr.shape),
            root_rank=root_rank,
            process_set_id=ps_id,
            process_set_size=ps_size,
        )
        h = self.handles.allocate()
        return self._enqueue(
            TensorTableEntry(name, arr, h, req, root_rank=root_rank))

    def alltoall_async(self, name, array, splits=None, process_set=None):
        arr = np.ascontiguousarray(array)
        ps_id, ps_size = self._ps_fields(process_set)
        n = ps_size or self.size
        if splits is not None:
            splits = [int(s) for s in splits]
            if len(splits) != n:
                raise ValueError(
                    f"alltoall needs one split per participant ({n})")
            if sum(splits) != arr.shape[0]:
                raise ValueError("splits must sum to dim 0")
        elif arr.ndim and arr.shape[0] % n:
            raise ValueError(
                "alltoall without splits requires dim 0 divisible by "
                "the participant count")
        req = Request(
            request_rank=self.rank,
            request_type=RequestType.ALLTOALL,
            tensor_type=dtype_from_numpy(arr.dtype),
            tensor_name=name,
            device="cpu",
            tensor_shape=TensorShape(arr.shape),
            process_set_id=ps_id,
            process_set_size=ps_size,
        )
        h = self.handles.allocate()
        entry = TensorTableEntry(name, arr, h, req, splits=splits)
        return self._enqueue(entry)

    def barrier(self, process_set=None):
        # Dedicated per-engine barrier counters (NOT the handle counter,
        # and one per process set): the name must be identical on every
        # member regardless of how many other ops each rank has issued,
        # and wire-compatible with the native engine's naming
        # (csrc/engine.cc Engine::Barrier).
        ps_id, ps_size = self._ps_fields(process_set)
        with self._queue_lock:
            c = self._barrier_counters.get(ps_id, 0)
            self._barrier_counters[ps_id] = c + 1
        # Distinct name families keep a concurrent global barrier and a
        # set barrier from colliding in the local duplicate-name guard.
        name = f"__barrier.{c}" if not ps_id else \
            f"__barrier.ps{ps_id}.{c}"
        req = Request(request_rank=self.rank,
                      request_type=RequestType.BARRIER,
                      tensor_type=DataType.INT32,
                      tensor_name=name, device="cpu",
                      process_set_id=ps_id, process_set_size=ps_size)
        h = self.handles.allocate()
        self._enqueue(TensorTableEntry(
            name, np.zeros(1, np.int32), h, req))
        return self.handles.wait(h)

    def join(self) -> int:
        """Block until every rank has joined; parity: §3.5 of SURVEY.md."""
        req = Request(request_rank=self.rank, request_type=RequestType.JOIN,
                      tensor_name="__join__", device="cpu")
        h = self.handles.allocate()
        with self._queue_lock:
            self._joined = True
            self._join_handle = h
            self._request_queue.append(req)
        self.handles.wait(h)
        return self._last_joined_rank

    def shutdown(self):
        # Cleanup must run exactly once — but it must run even when the
        # loop was already stopped by a PEER's negotiated shutdown (the
        # normal case on every non-initiating rank), so the guard is a
        # dedicated cleanup flag, not the loop-stop flags.
        if self._closed:
            return
        self._closed = True
        # Negotiated shutdown (parity: controller.cc:116-130): the next
        # worker/coordinator cycle carries the shutdown bit, the
        # coordinator's ResponseList stops every rank in the same cycle,
        # and only then do sockets close — no rank reads a socket its
        # peer already closed.  Bounded in case peers are already gone.
        self._shutdown_requested.set()
        self._loop_exited.wait(timeout=10)
        self._shutdown_flag.set()
        self._bg.join(timeout=10)
        self.timeline.shutdown()
        trace_mod.release(self._tracer)
        self._tracer = None
        # Stop the persistent senders first (drains queued frames while
        # the sockets are still open), then close sockets — which also
        # unblocks any sender stuck mid-write to a dead peer — and join.
        # Shm transports go first: their close drains, breaks any writer
        # spinning on a dead peer's full ring via the stop flag, joins
        # the hvd-send-shm-* thread, and unmaps the segment (the /dev/shm
        # name was already unlinked at pairing time, so nothing can leak
        # even if this process dies before reaching here).
        # Ladder mode: stop accepting reconnect re-dials before links
        # close, so no freshly-routed socket lands on a dying link.
        rl = getattr(self, "_reconnect_listener", None)
        if rl is not None:
            try:
                rl.close()
            except Exception:
                pass
        transports = list(getattr(self, "_transports", {}).values())
        for t in transports:
            if t.kind != "tcp":
                try:
                    t.close(timeout=2.0)
                except Exception:
                    pass
        senders = list(getattr(self, "_senders", {}).values())
        for snd in senders:
            try:
                snd.close(timeout=2.0)
            except Exception:
                pass
        self._senders = {}
        for s in list(self._data.values()) + list(self._ctrl_socks.values()):
            try:
                s.close()
            except OSError:
                pass
        if self._ctrl_sock is not None:
            try:
                self._ctrl_sock.close()
            except OSError:
                pass
        # Tree links (chan-2 bootstrap sockets): closing them is what
        # unblocks the child/parent recv threads; the _closed flag above
        # keeps the EOF from reading as a dead sub-coordinator.
        tree_socks = list(self._tree_child_socks.values())
        if self._tree_parent_sock is not None:
            tree_socks.append(self._tree_parent_sock)
        for s in tree_socks:
            try:
                s.close()
            except OSError:
                pass
        # Closed sockets error out any sender blocked in a write; bound
        # the join so shutdown stays prompt even for a wedged thread.
        for snd in senders:
            snd.thread.join(timeout=2.0)
        for t in transports:
            try:
                t.join(timeout=2.0)
            except Exception:
                pass
        self._transports = {}

    # ------------------------------------------------------------------
    # background loop
    # ------------------------------------------------------------------

    def _background_loop(self):
        try:
            while not self._shutdown_flag.is_set():
                t0 = time.monotonic()
                self.timeline.mark_cycle_start()
                if not self._run_loop_once():
                    break
                dt = time.monotonic() - t0
                _tmx.inc_counter("hvd_cycles_total")
                _tmx.observe("hvd_cycle_duration_seconds", dt)
                if self.rank == 0:
                    # Root coordination cost, keyed by gang size — the
                    # curve bench.py's ctrl_sim sweep reports (and the
                    # number the hierarchical tree exists to flatten).
                    _tmx.observe("hvd_ctrl_cycle_seconds", dt,
                                 labels=(str(self.size),))
                if dt < self.cycle_time:
                    time.sleep(self.cycle_time - dt)
        except Exception as e:  # deliver failure to all pending handles
            if not (self._shutdown_requested.is_set()
                    or self._shutdown_flag.is_set()):
                self.log.error("background loop failed: %r", e)
            self._abort(str(e))
        finally:
            self._drain_on_shutdown()
            self._loop_exited.set()

    def _drain_on_shutdown(self):
        # Parity: SHUT_DOWN_ERROR delivered to pending callbacks
        # (operations.cc:515-521).
        with self._queue_lock:
            entries = list(self._table.values())
            self._table.clear()
            self._request_queue.clear()
            jh, self._join_handle = self._join_handle, None
        exc = self._abort_exc
        status = Status(StatusType.ABORTED,
                        self._abort_reason or "Horovod has been shut down.",
                        exc) if exc is not None else \
            Status.aborted("Horovod has been shut down.")
        for e in entries:
            self._release_name(e.name)
            self.handles.mark_done(e.handle, status, None)
        if jh is not None:
            self.handles.mark_done(jh, Status.ok(), None)

    def _run_loop_once(self) -> bool:
        _fi.fire("engine.cycle", str(self.rank))
        with self._queue_lock:
            msgs = self._request_queue
            self._request_queue = []
        _tmx.set_gauge("hvd_queue_depth", len(msgs))
        if self.rank == 0:
            return self._coordinator_cycle(msgs)
        return self._worker_cycle(msgs)

    # -- cache classification (both roles, background thread only) -------

    def _classify(self, msgs: List[Request]):
        """Split popped requests into (uncached requests, hit events).
        Parity: the cache check at the top of ComputeResponseList
        (controller.cc:171-200)."""
        requests: List[Request] = []
        hits: List[tuple] = []
        misses = 0
        for req in msgs:
            if req.tensor_name in self._resend_uncached:
                self._resend_uncached.discard(req.tensor_name)
                requests.append(req)
                continue
            if not self._cache_classify_enabled:
                requests.append(req)
                continue
            state, pos = self._cache.classify(req)
            if state == rcache.HIT:
                hits.append((req.tensor_name, pos))
            else:
                requests.append(req)
                misses += 1
        if hits:
            _tmx.inc_counter("hvd_cache_hits_total", len(hits))
        if misses:
            _tmx.inc_counter("hvd_cache_misses_total", misses)
        return requests, hits

    def _execute_cached_hits(self, hit_positions: List[int]) -> None:
        cached: List[Response] = []
        for p in hit_positions:
            resp = self._cache.get_by_position(p)
            if resp is None:
                # A missing position means this rank's cache diverged from
                # the coordinator's.  Executing the remaining hits would
                # launch a different collective sequence than the other
                # ranks and hang the whole job — fail fast instead.
                self.log.error(
                    "cache coherence violation: position %d missing "
                    "locally, aborting", p)
                self._abort(f"cache coherence violation: position {p}")
                return
            self._cache.touch(p)
            # Copy: _fuse_responses mutates its inputs in place, and the
            # cached Response must stay single-tensor.
            cached.append(Response(
                response_type=resp.response_type,
                tensor_type=resp.tensor_type,
                tensor_names=list(resp.tensor_names),
                devices=list(resp.devices),
                tensor_sizes=list(resp.tensor_sizes),
                reduce_op=resp.reduce_op,
                prescale_factor=resp.prescale_factor,
                postscale_factor=resp.postscale_factor,
                tensor_shapes=list(resp.tensor_shapes),
            ))
        for resp in self._fuse_responses(cached):
            self._perform_operation(resp, from_cache=True)

    def _process_resends(self, resend_names: List[str]) -> None:
        """Coordinator could not resolve our hit event (entry evicted
        there in flight): requeue the original full Request."""
        with self._queue_lock:
            for nm in resend_names:
                ent = self._table.get(nm)
                if ent is not None:
                    self._resend_uncached.add(nm)
                    self._request_queue.append(ent.request)

    # -- worker ---------------------------------------------------------

    def _maybe_clock_ping(self) -> None:
        """Tracing only: piggyback a clock-offset ping on the ctrl
        channel at bootstrap and every HVD_TRACE_CLOCK_SYNC_CYCLES
        worker cycles (docs/timeline.md "Gang-wide tracing")."""
        n = self._clock_ping_countdown
        if n > 0:
            self._clock_ping_countdown = n - 1
            return
        self._clock_ping_countdown = self._clock_sync_cycles
        try:
            ping = wire.encode_clock_ping(time.monotonic_ns(), self.epoch)
            with self._ctrl_send_lock:
                su.send_frame(self._ctrl_sock, su.TAG_CLOCK_PING, ping)
            self._last_send = time.monotonic()
        except (ConnectionError, OSError):
            pass  # a dead hub surfaces through the recv loop

    def _worker_cycle(self, msgs: List[Request]) -> bool:
        if self._fenced is not None:
            # The coordinator told us we have no seat in the re-formed
            # gang (TAG_FENCE): deliver the typed error and stop before
            # another frame of ours can touch the new incarnation.
            stale, current = self._fenced
            exc = FencedError("control", stale, current)
            self._abort(str(exc), exc=exc)
            return False
        if self._tracer is not None:
            self._maybe_clock_ping()
        requests, hit_events = self._classify(msgs)
        want_shutdown = self._shutdown_requested.is_set()
        send_failed = False
        # Sub-coordinator: everything the children uplinked since the
        # last cycle folds into one TAG_TREE_UP alongside our own frame.
        tree_entries: List[tuple] = []
        if self._tree_child_socks:
            with self._tree_up_lock:
                tree_entries = self._tree_up_buf
                self._tree_up_buf = []
        if requests or hit_events or want_shutdown:
            payload = wire.encode_request_list(requests,
                                               shutdown=want_shutdown,
                                               cache_hits=hit_events,
                                               epoch=self.epoch)
            if self._tree_child_socks:
                tree_entries.append(
                    (self.rank, su.TAG_REQUEST_LIST, payload))
            else:
                try:
                    _fi.fire("ctrl.worker.send", str(self.rank))
                    if self._tree_parent is not None \
                            and not self._tree_orphaned \
                            and self._tree_parent_sock is not None:
                        # Uplink via our host's sub-coordinator; keep the
                        # payload so a re-parent can replay the frames a
                        # dying parent may never have forwarded.
                        with self._ctrl_send_lock:
                            su.send_frame(self._tree_parent_sock,
                                          su.TAG_REQUEST_LIST, payload)
                        self._tree_unacked.append(payload)
                        del self._tree_unacked[:-8]
                    else:
                        with self._ctrl_send_lock:
                            su.send_frame(self._ctrl_sock,
                                          su.TAG_REQUEST_LIST, payload)
                    self._last_send = time.monotonic()
                except (ConnectionError, OSError):
                    if self._tree_parent is not None \
                            and not self._tree_orphaned:
                        # Dead sub-coordinator, not a dead hub: adopt
                        # ourselves back to the star (which replays the
                        # unacked frames, this one included).
                        self._tree_unacked.append(payload)
                        del self._tree_unacked[:-8]
                        self._reparent_to_root()
                    else:
                        # The coordinator may have closed right after
                        # broadcasting a shutdown ResponseList; the
                        # receiver thread may already hold it — drain
                        # before concluding the peer was genuinely lost.
                        send_failed = True
        elif self.heartbeat_timeout > 0 and \
                time.monotonic() - self._last_send >= self.heartbeat_interval:
            # Idle past the heartbeat cadence: prove liveness.  A lost
            # coordinator surfaces through the recv loop, not here.
            if self._tree_child_socks:
                tree_entries.append((self.rank, su.TAG_HEARTBEAT, b""))
            else:
                hb_sock = self._ctrl_sock
                if self._tree_parent is not None \
                        and not self._tree_orphaned \
                        and self._tree_parent_sock is not None:
                    hb_sock = self._tree_parent_sock
                try:
                    with self._ctrl_send_lock:
                        su.send_frame(hb_sock, su.TAG_HEARTBEAT, b"")
                except (ConnectionError, OSError):
                    if hb_sock is self._tree_parent_sock:
                        self._reparent_to_root()
            self._last_send = time.monotonic()
        if tree_entries:
            up = wire.encode_tree_up(tree_entries, epoch=self.epoch)
            try:
                _fi.fire("ctrl.subcoord.send", str(self.rank))
                with self._ctrl_send_lock:
                    su.send_frame(self._ctrl_sock, su.TAG_TREE_UP, up)
                self._last_send = time.monotonic()
            except (ConnectionError, OSError):
                send_failed = True
        with self._response_lock:
            inbox = self._response_inbox
            self._response_inbox = []
        for payload in inbox:
            responses, shutdown, hit_positions, resend, params, epoch = \
                wire.decode_response_list(payload)
            if epoch != self.epoch:
                # Stale incarnation (a coordinator we were re-formed away
                # from, or one we have not re-formed to yet): executing
                # its responses would desync this gang.  Drop the frame.
                self.log.warning(
                    "dropping response frame from epoch %d (ours: %d)",
                    epoch, self.epoch)
                continue
            if params is not None:
                # Apply BEFORE executing this frame's hits: the fusion
                # threshold shapes the fused launches, which must be
                # identical on every rank.
                self._apply_params(params)
            self._process_resends(resend)
            self._execute_cached_hits(hit_positions)
            for resp in responses:
                self._perform_operation(resp)
            if shutdown:
                self._shutdown_flag.set()
                return False
        if send_failed or self._ctrl_conn_lost:
            # A send failure or a recv-thread EOF both mean the hub is
            # unreachable — but a shutdown ResponseList may have landed
            # in the inbox between the drain above and now.  Drain once
            # more so clean teardown never masquerades as a dead hub.
            with self._response_lock:
                late = self._response_inbox
                self._response_inbox = []
            for payload in late:
                decoded = wire.decode_response_list(payload)
                if decoded[1] and decoded[5] == self.epoch:  # shutdown
                    self._shutdown_flag.set()
                    return False
            self._abort("lost connection to coordinator")
            return False
        return True

    def _apply_params(self, params) -> None:
        # 5-tuple frames come from older coordinators (and the native
        # engine) that predate the ring-segment knob; keep the local
        # setting in that case.
        fusion, cycle_s, cache_on, hier_ar, hier_ag = params[:5]
        self.fusion_threshold = fusion
        self.cycle_time = cycle_s
        self._cache_classify_enabled = cache_on
        self.hierarchical_allreduce = hier_ar
        self.hierarchical_allgather = hier_ag
        if len(params) > 5:
            self.ring_segment_bytes = params[5]

    def hierarchical_topology_ok(self) -> bool:
        """True when the two-level data plane can run: a real local/cross
        split and the launcher's homogeneous block rank layout."""
        from horovod_tpu.runner.discovery import block_topology_ok

        return block_topology_ok(self.rank, self.size, self.local_rank,
                                 self.local_size, self.cross_rank,
                                 self.cross_size)

    # -- coordinator ----------------------------------------------------

    def _coordinator_cycle(self, msgs: List[Request]) -> bool:
        ready: List[str] = []
        shutdown = self._shutdown_requested.is_set()
        # names this cycle asks specific ranks to resend in full
        resend_by_rank: Dict[int, List[str]] = {}

        def _absorb(req: Request) -> None:
            nonlocal ready, shutdown
            if req.request_type == RequestType.JOIN:
                self._joined_ranks.add(req.request_rank)
                self._last_joined_rank = req.request_rank
                # Tensors waiting only on joined ranks become ready
                # (global-set entries only; join never applies to
                # process-set traffic).
                for nm, lst in list(self._msg_table.entries.items()):
                    if lst[0].process_set_id == 0 and \
                            len(lst) == self.size - len(self._joined_ranks):
                        if nm not in ready:
                            ready.append(nm)
                return
            if self.timeline.enabled:
                # Start on the FIRST request for this key — a process
                # set may not contain rank 0, and an End without a
                # Start corrupts the trace.
                key = _MessageTable.key_of(req)
                if key not in self._msg_table.entries:
                    self.timeline.negotiate_start(
                        req.tensor_name, _OP_NAMES[req.request_type])
                self.timeline.negotiate_rank_ready(
                    req.tensor_name, req.request_rank)
            if self._straggler is not None:
                self._straggler.note_ready(
                    _MessageTable.key_of(req), req.request_rank)
            if self._msg_table.increment(req, len(self._joined_ranks)):
                ready.append(_MessageTable.key_of(req))

        def _absorb_hit(name: str, pos: int, rank: int) -> None:
            # A hit event stands for the full Request; rebuild it from
            # our own cache (coherent with the sender's) and let it ride
            # the ordinary message table.  If our entry was evicted in
            # flight, ask the sender to resend the full request.
            if self._cache.name_at(pos) != name:
                resend_by_rank.setdefault(rank, []).append(name)
                return
            req = self._cache.synthesize_request(pos, rank)
            self._hit_ranks.setdefault(name, set()).add(rank)
            _absorb(req)

        requests, own_hits = self._classify(msgs)
        for req in requests:
            _absorb(req)
        for name, pos in own_hits:
            _absorb_hit(name, pos, 0)
        with self._ctrl_lock:
            inbox = self._ctrl_inbox
            self._ctrl_inbox = []
        for peer, payload in inbox:
            reqs, peer_shutdown, peer_hits, peer_epoch = \
                wire.decode_request_list(payload)
            if peer_epoch != self.epoch:
                # A zombie from a previous incarnation (evicted but not
                # dead, now reconnected through a stale socket): absorbing
                # its requests would hang or corrupt this gang's
                # negotiation — reject the frame before it touches the
                # message table, and tell the sender WHY with a typed
                # TAG_FENCE so it raises FencedError and exits instead
                # of retrying forever against a gang it has no seat in.
                self.log.warning(
                    "rejecting request frame from rank %d at epoch %d "
                    "(ours: %d)", peer, peer_epoch, self.epoch)
                _tmx.inc_counter("hvd_fenced_writes_total")
                blackbox_mod.note("epoch.fence", time.monotonic_ns(),
                                  rank=peer, stale_epoch=peer_epoch,
                                  epoch=self.epoch)
                fsock = self._ctrl_socks.get(peer)
                if fsock is not None:
                    try:
                        with self._ctrl_send_lock:
                            su.send_frame(
                                fsock, su.TAG_FENCE,
                                wire.encode_fence(peer_epoch, self.epoch))
                    except (ConnectionError, OSError):
                        pass
                continue
            shutdown = shutdown or peer_shutdown
            for req in reqs:
                _absorb(req)
            for name, pos in peer_hits:
                _absorb_hit(name, pos, peer)

        # Hang detection: a worker's hop deadline fired while we are
        # demonstrably healthy (running cycles) — rule on the abort now
        # rather than waiting to block in the collective ourselves.
        if self.collective_timeout > 0:
            self._drain_abort_reports()

        # Liveness: evict ranks silent past the heartbeat timeout (or
        # whose ctrl connection dropped), reusing the Join readiness
        # machinery so survivors complete in-flight negotiation.
        dead = self._check_dead_ranks()
        if dead and not shutdown:
            self._evict_ranks(dead, ready)

        responses: List[Response] = []
        hit_positions: List[int] = []
        for key in ready:
            t_first = self._msg_table.first_seen.get(key) \
                if self._metrics_on else None
            reqs = self._msg_table.pop(key)
            name = reqs[0].tensor_name  # key may be set-scoped
            if self.timeline.enabled:
                self.timeline.negotiate_end(name)
            if t_first is not None:
                _tmx.observe("hvd_negotiation_seconds",
                             time.monotonic() - t_first)
            if self._straggler is not None:
                lagger = self._straggler.note_complete(key)
                if lagger is not None:
                    self._emit_straggler(name, *lagger)
            # Hits are global-set-only, where key == name; popping by key
            # keeps a set-scoped completion from stealing a same-named
            # global tensor's hit record.
            hit_ranks = self._hit_ranks.pop(key, set())
            contributors = {r.request_rank for r in reqs}
            ent_pos = -1
            # An eviction cycle must ship full responses: workers apply
            # cached hits BEFORE the response stream, which would run a
            # collective over the old group before seeing the EVICT.
            if not dead and hit_ranks >= contributors:
                # Every contributor hit → all requests were synthesized
                # from the same cache entry → the negotiated response IS
                # the cached one; broadcast just the position.
                ent_pos = self._cache.position_of(name)
            if ent_pos >= 0:
                hit_positions.append(ent_pos)
            else:
                responses.append(self._construct_response(name, reqs))

        if dead and not shutdown:
            # First in the stream: every rank applies the eviction before
            # executing any collective made ready by it.
            responses.insert(0, Response(
                response_type=ResponseType.EVICT,
                tensor_sizes=sorted(dead)))

        if len(self._joined_ranks) == self.size:
            responses.append(Response(
                response_type=ResponseType.JOIN,
                tensor_sizes=[self._last_joined_rank]))
            # Evicted ranks never un-join: re-seed so post-join traffic
            # keeps counting them out of readiness.
            self._joined_ranks = set(self._evicted_ranks)

        if not self.stall_check_disable:
            shutdown = self._check_stalls() or shutdown

        tuned = self._pending_params
        if responses or hit_positions or resend_by_rank or shutdown \
                or tuned is not None:
            fused = self._fuse_responses(responses)
            if self._metrics_on:
                for resp in fused:
                    if resp.tensor_names and resp.tensor_type is not None:
                        _tmx.observe(
                            "hvd_fused_bytes",
                            sum(resp.tensor_sizes)
                            * resp.tensor_type.itemsize)
                        _tmx.observe("hvd_fused_tensors",
                                     len(resp.tensor_names))
            params = None
            if tuned is not None:
                params = (tuned.fusion_threshold, tuned.cycle_time_s,
                          tuned.cache_enabled,
                          tuned.hierarchical_allreduce,
                          tuned.hierarchical_allgather,
                          getattr(tuned, "ring_segment_bytes",
                                  self.ring_segment_bytes))
                self._pending_params = None
            shared = None
            for r, s in self._ctrl_socks.items():
                resend = resend_by_rank.get(r, [])
                if resend:
                    payload = wire.encode_response_list(
                        fused, shutdown=shutdown,
                        hit_positions=hit_positions, resend_names=resend,
                        params=params, epoch=self.epoch)
                else:
                    if shared is None:
                        shared = wire.encode_response_list(
                            fused, shutdown=shutdown,
                            hit_positions=hit_positions, params=params,
                            epoch=self.epoch)
                    payload = shared
                try:
                    _fi.fire("ctrl.coord.send", str(r))
                    with self._ctrl_send_lock:
                        su.send_frame(s, su.TAG_RESPONSE_LIST, payload)
                except (ConnectionError, OSError):
                    pass
            if params is not None:
                # Same ordering contract as the workers: apply before
                # fusing/executing this frame's cached hits.
                self._apply_params(params)
            self._execute_cached_hits(hit_positions)
            for resp in fused:
                self._perform_operation(resp)
            if self._pm is not None and not self._pm.done:
                nbytes = sum(
                    sum(r.tensor_sizes) * r.tensor_type.itemsize
                    for r in fused
                    if r.response_type == ResponseType.ALLREDUCE)
                nbytes += sum(
                    c.tensor_sizes[0] * c.tensor_type.itemsize
                    for c in map(self._cache.get_by_position, hit_positions)
                    if c is not None)
                new = self._pm.record_bytes(nbytes)
                if new is not None:
                    self._pending_params = new
            if shutdown:
                self._shutdown_flag.set()
                return False
        return True

    def _check_dead_ranks(self) -> List[int]:
        """Ranks whose ctrl connection dropped or that have been silent
        past the heartbeat timeout.  Empty unless liveness is enabled
        (HVD_HEARTBEAT_TIMEOUT > 0)."""
        if self.heartbeat_timeout <= 0:
            return []
        now = time.monotonic()
        dead = []
        for r, t in self._last_seen.items():
            if r in self._evicted_ranks:
                continue
            if r in self._conn_lost or now - t > self.heartbeat_timeout:
                dead.append(r)
        # Orphan grace: a dying sub-coordinator takes its children's
        # uplink with it, so their silence is HIS fault, not theirs.
        # Give every rank still routed through a freshly-dead parent a
        # full timeout window to re-parent and heartbeat directly — only
        # the dead parent is evicted this round.
        if dead and self._rank_route:
            dead_set = set(dead)
            for child, parent in list(self._rank_route.items()):
                if parent in dead_set and child in dead_set:
                    dead.remove(child)
                    self._last_seen[child] = now
                    self._conn_lost.discard(child)
        return dead

    def _evict_ranks(self, dead: List[int], ready: List[str]) -> None:
        """Treat ``dead`` as permanently joined: drop their pending
        requests and rescan readiness so survivors complete the in-flight
        negotiation with zero stand-ins (the Join contract)."""
        for r in dead:
            self.log.error(
                "rank %d unresponsive (%s); evicting from the job", r,
                "connection lost" if r in self._conn_lost
                else f"no heartbeat for {self.heartbeat_timeout:.1f}s")
            if r not in self._conn_lost:
                _tmx.inc_counter("hvd_heartbeat_misses_total")
            _tmx.inc_counter("hvd_evictions_total")
            blackbox_mod.note("heartbeat.miss", time.monotonic_ns(),
                              rank=r,
                              conn_lost=bool(r in self._conn_lost))
            self._evicted_ranks.add(r)
            self._joined_ranks.add(r)
        for nm, lst in list(self._msg_table.entries.items()):
            lst[:] = [q for q in lst
                      if q.request_rank not in self._evicted_ranks]
            if not lst:
                # Only dead ranks had announced it; no survivor holds an
                # entry, so nothing to complete.
                self._msg_table.pop(nm)
                self._hit_ranks.pop(nm, None)
                if self._straggler is not None:
                    self._straggler.forget(nm)
                if nm in ready:
                    ready.remove(nm)
            elif lst[0].process_set_id == 0 and \
                    len(lst) == self.size - len(self._joined_ranks) and \
                    nm not in ready:
                ready.append(nm)

    def _emit_straggler(self, name: str, lag_rank: int,
                        skew_s: float) -> None:
        """The straggler detector tripped: one rank has been last to
        negotiate for several consecutive tensors by more than
        HVD_STRAGGLER_WARN_MS.  Record it on the timeline and warn; the
        detector re-arms, so records are naturally throttled."""
        self.log.warning(
            "straggler: rank %d consistently last to negotiate "
            "(skew %.1f ms on %s)", lag_rank, skew_s * 1e3, name)
        if self.timeline.enabled:
            self.timeline.instant(
                timeline_mod.STRAGGLER, rank=lag_rank,
                skew_ms=round(skew_s * 1e3, 3), tensor=name)
        blackbox_mod.note("straggler", 0, rank=lag_rank,
                          skew_ms=round(skew_s * 1e3, 3), name=name)

    # -- collective-abort agreement (docs/fault_tolerance.md) ------------
    #
    # Heartbeats catch DEAD ranks; these four frames catch HUNG ones.
    # A rank whose ring hop blows HVD_COLLECTIVE_TIMEOUT reports the
    # suspect peer to the coordinator over the still-live control
    # channel (TAG_ABORT_REPORT).  The coordinator probes the gang
    # (TAG_PROBE / TAG_PROBE_ACK — answered from the recv thread, which
    # stays responsive even while the background thread is wedged in
    # the data plane), rules on who is actually stuck, and broadcasts
    # TAG_ABORT_VERDICT so every survivor raises the SAME
    # CollectiveTimeoutError for the SAME step.

    def _drain_abort_reports(self) -> None:
        """Coordinator, between cycles (i.e. not itself blocked in a
        collective): act on hop-timeout reports that arrived while we
        were healthy."""
        with self._abort_lock:
            if not self._abort_inbox:
                return
            inbox, self._abort_inbox = self._abort_inbox, []
        reports: Dict[int, int] = {}
        name = ""
        for peer, tag, payload in inbox:
            if tag != su.TAG_ABORT_REPORT:
                continue  # stray ack from an already-finished probe round
            nm, suspect, epoch = wire.decode_abort_report(payload)
            if epoch != self.epoch:
                continue
            if self._last_verdict is not None and \
                    self._last_verdict[0] == nm:
                # Already ruled: this straggler's own hop deadline fired
                # after the broadcast — re-send the verdict.
                self._send_verdict_to(peer)
                continue
            reports[peer] = suspect
            name = nm
        if reports:
            self._coordinate_abort(name, reports)

    def _send_verdict_to(self, rank: int) -> None:
        vname, vranks = self._last_verdict
        sock = self._ctrl_socks.get(rank)
        if sock is None:
            return
        try:
            with self._ctrl_send_lock:
                su.send_frame(
                    sock, su.TAG_ABORT_VERDICT,
                    wire.encode_abort_verdict(vname, vranks, self.epoch))
        except (ConnectionError, OSError):
            pass

    def _coordinate_abort(self, name: str,
                          reports: Dict[int, int]) -> List[int]:
        """Probe the gang, rule on which rank(s) are wedged, broadcast
        and apply the verdict.  Runs on the coordinator's background
        thread — from _drain_abort_reports (coordinator healthy) or
        from its own HopTimeout (coordinator was blocked in the stalled
        collective too).  ``reports`` maps reporter rank -> the peer it
        blamed.  Returns the agreed wedged ranks."""
        t0 = time.monotonic()
        self.log.error(
            "collective %r blew its %gs deadline (reported by rank(s) "
            "%s); probing the gang", name, self.collective_timeout,
            sorted(reports))
        live = [r for r in self._ctrl_socks
                if r not in self._evicted_ranks]
        acks: Dict[int, tuple] = {}

        def _probe() -> None:
            for r in live:
                # Ranks folded under a live sub-coordinator get their
                # probe routed down the tree (one hop, same host); the
                # ack always returns on the rank's DIRECT socket.  A
                # dead or evicted parent falls back to the direct link.
                parent = self._rank_route.get(r)
                if parent is not None and parent in self._ctrl_socks \
                        and parent not in self._evicted_ranks \
                        and parent not in self._conn_lost:
                    down = wire.encode_tree_down(r, su.TAG_PROBE, b"")
                    try:
                        with self._ctrl_send_lock:
                            su.send_frame(self._ctrl_socks[parent],
                                          su.TAG_TREE_DOWN, down)
                        continue
                    except (ConnectionError, OSError):
                        pass
                try:
                    with self._ctrl_send_lock:
                        su.send_frame(self._ctrl_socks[r],
                                      su.TAG_PROBE, b"")
                except (ConnectionError, OSError):
                    pass

        _probe()
        deadline = t0 + max(0.1, self.collective_probe_timeout)
        last_probe = t0
        while time.monotonic() < deadline:
            with self._abort_lock:
                inbox, self._abort_inbox = self._abort_inbox, []
            for peer, tag, payload in inbox:
                if tag == su.TAG_PROBE_ACK:
                    busy, busy_s, ep = wire.decode_probe_ack(payload)
                    if ep == self.epoch:
                        acks[peer] = (busy, busy_s)
                elif tag == su.TAG_ABORT_REPORT:
                    nm, suspect, ep = wire.decode_abort_report(payload)
                    if ep == self.epoch:
                        reports[peer] = suspect
            # Converged: every live worker has either reported a timeout
            # of its own (a victim of the hang, not its cause) or acked
            # idle — nothing left to learn from the rest of the window.
            if all(r in reports or (r in acks and not acks[r][0])
                   for r in live):
                break
            now = time.monotonic()
            if now - last_probe >= 0.25:
                _probe()  # refresh busy durations
                last_probe = now
            time.sleep(0.02)

        # Verdict: a live rank is wedged when it never reported a hop
        # timeout of its own AND its last word was "busy" (or silence).
        # Every healthy participant's own deadline fires within ~one
        # collective timeout of the first, so by the window's end the
        # busy-and-silent ranks are the truly stuck ones.
        wedged = sorted(
            r for r in live
            if r not in reports and (r not in acks or acks[r][0]))
        if not wedged:
            # Nobody provably stuck (hang healed mid-probe, or the
            # victim died and took its socket along): fall back on the
            # most-blamed suspect, preferring non-reporters; ties go to
            # the lowest rank so every coordinator incarnation would
            # rule identically.
            blame: Dict[int, int] = {}
            for suspect in reports.values():
                if suspect >= 0 and suspect not in reports:
                    blame[suspect] = blame.get(suspect, 0) + 1
            if not blame:
                for suspect in reports.values():
                    if suspect >= 0:
                        blame[suspect] = blame.get(suspect, 0) + 1
            if blame:
                top = max(blame.values())
                wedged = [min(r for r, n in blame.items() if n == top)]

        payload = wire.encode_abort_verdict(name, wedged, self.epoch)
        self._last_verdict = (name, wedged)
        for r in live:
            try:
                with self._ctrl_send_lock:
                    su.send_frame(self._ctrl_socks[r],
                                  su.TAG_ABORT_VERDICT, payload)
            except (ConnectionError, OSError):
                pass
        self._apply_abort_verdict(name, wedged, t0)
        # Archive the evidence: pull every live rank's flight-recorder
        # ring (INCLUDING the wedged ones — their ctrl recv thread stays
        # responsive while the background thread hangs in the data
        # plane) so one dump directory survives even when a rank's own
        # disk write never lands.
        self._pull_blackbox_dumps(live)
        return wedged

    def _pull_blackbox_dumps(self, ranks: List[int],
                             wait_s: float = 1.0) -> None:
        """Coordinator: request TAG_BLACKBOX dumps from ``ranks`` and
        write whatever arrives within ``wait_s`` as
        ``blackbox_rank<r>.pulled.json`` in our own HVD_BLACKBOX_DIR.
        Best-effort evidence collection — never raises."""
        bb = blackbox_mod.get()
        if bb is None or not ranks:
            return
        req = wire.encode_blackbox_request(self.epoch)
        asked = []
        for r in ranks:
            sock = self._ctrl_socks.get(r)
            if sock is None:
                continue
            try:
                with self._ctrl_send_lock:
                    su.send_frame(sock, su.TAG_BLACKBOX, req)
                asked.append(r)
            except (ConnectionError, OSError):
                pass
        got: set = set()
        deadline = time.monotonic() + wait_s
        while len(got) < len(asked) and time.monotonic() < deadline:
            with self._blackbox_lock:
                inbox, self._blackbox_inbox = self._blackbox_inbox, []
            for peer, payload in inbox:
                try:
                    drank, depoch, blob = wire.decode_blackbox_dump(
                        payload)
                    os.makedirs(bb.dir, exist_ok=True)
                    path = os.path.join(
                        bb.dir, f"blackbox_rank{drank}.pulled.json")
                    tmp = f"{path}.tmp.{os.getpid()}"
                    with open(tmp, "wb") as fh:
                        fh.write(blob)
                    os.replace(tmp, path)
                    got.add(peer)
                except Exception:
                    got.add(peer)
            if len(got) < len(asked):
                time.sleep(0.02)
        if asked:
            self.log.info(
                "flight-recorder archive: pulled %d/%d worker dumps "
                "into %s", len(got), len(asked), bb.dir)

    def _report_and_await_verdict(self, name: str,
                                  suspect: int) -> Optional[List[int]]:
        """Worker half of the agreement: report the local hop timeout,
        then block (on the background thread — the collective is dead
        anyway) until the verdict lands.  None = no verdict in time,
        i.e. the coordinator itself is wedged or lost."""
        with self._abort_cv:
            if self._abort_verdict is not None:
                # Broadcast already arrived while this rank was still
                # blocked in the data plane.
                ranks = self._abort_verdict[1]
                self._abort_verdict = None
                return ranks
        try:
            with self._ctrl_send_lock:
                su.send_frame(
                    self._ctrl_sock, su.TAG_ABORT_REPORT,
                    wire.encode_abort_report(name, suspect, self.epoch))
        except (ConnectionError, OSError):
            return None
        # Budget: worst case the coordinator only starts probing after
        # its OWN hop deadline (one collective timeout), then runs a
        # full probe window.
        deadline = time.monotonic() + max(
            2.0 * self.collective_timeout,
            self.collective_timeout + 2.0 * self.collective_probe_timeout)
        with self._abort_cv:
            while self._abort_verdict is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._abort_cv.wait(remaining)
            ranks = self._abort_verdict[1]
            self._abort_verdict = None
        return ranks

    def _apply_abort_verdict(self, name: str, ranks: List[int],
                             t0: float) -> None:
        """Record + apply an agreed abort: timeline record, metrics,
        eviction state (so the next enqueue raises on every survivor
        and the elastic wrapper re-forms without the wedged ranks)."""
        elapsed = time.monotonic() - t0
        _tmx.inc_counter("hvd_collective_timeouts_total")
        _tmx.observe("hvd_collective_abort_seconds", elapsed)
        if self.timeline.enabled:
            self.timeline.instant(
                timeline_mod.COLLECTIVE_ABORT, ranks=list(ranks),
                tensor=name, abort_ms=round(elapsed * 1e3, 3))
        self.log.error(
            "gang verdict: rank(s) %s wedged during %r; aborting the "
            "collective (%.0f ms after the local timeout)", ranks, name,
            elapsed * 1e3)
        # Terminal event: record the verdict and dump the flight
        # recorder (failure path — the clock read here is free).
        blackbox_mod.note("abort.verdict", time.monotonic_ns(),
                          ranks=list(ranks), name=name,
                          abort_ms=round(elapsed * 1e3, 3))
        blackbox_mod.dump("collective_timeout",
                          f"wedged={list(ranks)} name={name}")
        self._evicted_ranks.update(ranks)
        self._ranks_failed = sorted(set(self._ranks_failed) | set(ranks))
        if self.rank == 0 and self._msg_table is not None:
            # Same pruning as a heartbeat eviction, minus the liveness
            # bookkeeping: drop the wedged ranks' pending requests so
            # the post-abort cycles cannot hang on them.
            self._joined_ranks.update(ranks)
            for nm, lst in list(self._msg_table.entries.items()):
                lst[:] = [q for q in lst
                          if q.request_rank not in self._evicted_ranks]
                if not lst:
                    self._msg_table.pop(nm)
                    self._hit_ranks.pop(nm, None)

    def _retain_for_replay(self, resp: Response,
                           entries: List[TensorTableEntry]) -> None:
        """Keep copies of the aborted fused reduction's ORIGINAL inputs
        (pack() copies; the ring never mutates entry.array) so the
        re-formed gang can replay the batch."""
        if resp.response_type != ResponseType.ALLREDUCE:
            return
        batch = [
            {"name": e.name, "array": np.array(e.array, copy=True),
             "op": resp.reduce_op, "prescale": resp.prescale_factor,
             "postscale": resp.postscale_factor}
            for e in entries if e.handle >= 0]
        if batch:
            retain_aborted_batch(batch)

    def _collective_abort(self, resp: Response,
                          entries: List[TensorTableEntry],
                          hop: Exception) -> Status:
        """A local hop deadline fired: run the gang-wide agreement and
        build the typed failure status every survivor shares."""
        name = resp.tensor_names[0]
        suspect = int(getattr(hop, "peer", -1))
        # Blame record: who THIS rank was blocked on when its deadline
        # fired — the postmortem triangulates the first cause from the
        # gang's blame edges (failure path; clock read is free).
        blackbox_mod.note("collective.timeout", time.monotonic_ns(),
                          name=name, peer=suspect,
                          phase=str(getattr(hop, "phase", "recv")))
        if self.rank == 0:
            wedged = self._coordinate_abort(name, {0: suspect})
        else:
            t0 = time.monotonic()
            wedged = self._report_and_await_verdict(name, suspect)
            if wedged is None:
                # The one rank that could rule never did: treat it like
                # a lost coordinator so the elastic wrapper re-forms
                # around rank 0.
                reason = ("collective timed out and no abort verdict "
                          "arrived: coordinator wedged or lost")
                self._abort(reason)
                return Status.aborted(reason)
            if self.rank in wedged:
                # The gang ruled *us* wedged (e.g. our probe acks never
                # made it out): the group has moved on without this
                # rank — stop before desyncing it.
                raise RuntimeError(
                    "evicted by the coordinator (collective timeout)")
            self._apply_abort_verdict(name, wedged, t0)
        self._retain_for_replay(resp, entries)
        err = CollectiveTimeoutError(wedged, name,
                                     self.collective_timeout)
        status = Status.aborted(str(err))
        status.exc = err
        return status

    def _check_stalls(self) -> bool:
        now = time.monotonic()
        if now - self._last_stall_check < self.stall_warn_s / 4:
            return False
        self._last_stall_check = now
        shutdown = False
        for name, t0 in self._msg_table.first_seen.items():
            waited = now - t0
            if waited > self.stall_warn_s:
                have = sorted(r.request_rank
                              for r in self._msg_table.entries[name])
                missing = [r for r in range(self.size)
                           if r not in have and
                           r not in self._joined_ranks]
                self.log.warning(
                    "Stalled tensor %s: ready on ranks %s, waiting on %s "
                    "for %.0fs", name, have, missing, waited)
                _tmx.inc_counter("hvd_stall_warnings_total")
                if self.stall_shutdown_s > 0 and \
                        waited > self.stall_shutdown_s:
                    self.log.error(
                        "Stalled tensor %s exceeded shutdown threshold; "
                        "shutting down", name)
                    shutdown = True
        return shutdown

    # -- response construction (parity: ConstructResponse) --------------

    def _construct_response(self, name: str, reqs: List[Request]) -> Response:
        first = reqs[0]
        err = None
        if any(r.request_type != first.request_type for r in reqs):
            err = (f"Mismatched collective operations for tensor {name}: "
                   + ", ".join(sorted({_OP_NAMES[r.request_type]
                                       for r in reqs})))
        elif any(r.process_set_id != first.process_set_id or
                 r.process_set_size != first.process_set_size
                 for r in reqs):
            err = f"Mismatched process sets for tensor {name}"
        elif first.process_set_id and \
                first.request_type == RequestType.JOIN:
            err = (f"{_OP_NAMES[first.request_type]} does not support "
                   f"process sets (tensor {name})")
        elif any(r.tensor_type != first.tensor_type for r in reqs):
            err = (f"Mismatched data types for tensor {name}: "
                   + ", ".join(sorted({r.tensor_type.name for r in reqs})))
        elif first.request_type == RequestType.ALLREDUCE:
            if any(r.tensor_shape != first.tensor_shape for r in reqs):
                err = (f"Mismatched allreduce tensor shapes for {name}: "
                       + ", ".join(sorted({str(r.tensor_shape)
                                           for r in reqs})))
            elif any(r.reduce_op != first.reduce_op for r in reqs):
                err = f"Mismatched reduce ops for tensor {name}"
            elif first.process_set_id and \
                    first.reduce_op == ReduceOp.ADASUM:
                err = (f"Adasum is not supported with process sets "
                       f"(tensor {name})")
        elif first.request_type == RequestType.BROADCAST:
            if any(r.root_rank != first.root_rank for r in reqs):
                err = (f"Mismatched broadcast root ranks for {name}: "
                       + ", ".join(sorted({str(r.root_rank)
                                           for r in reqs})))
            elif any(r.tensor_shape != first.tensor_shape for r in reqs):
                err = f"Mismatched broadcast tensor shapes for {name}"
            elif first.process_set_id:
                from horovod_tpu import process_sets

                members = process_sets.ranks_of(first.process_set_id)
                if members is not None and \
                        first.root_rank not in members:
                    # Authoritative check (wrappers pre-check too): a
                    # non-member root would skip while members block.
                    err = (f"broadcast root rank {first.root_rank} is "
                           f"not a member of process set "
                           f"{first.process_set_id} (tensor {name})")
        elif first.request_type == RequestType.ALLGATHER:
            for r in reqs:
                if r.tensor_shape.rank != first.tensor_shape.rank or \
                        r.tensor_shape.dims[1:] != first.tensor_shape.dims[1:]:
                    err = (f"Mismatched allgather tensor shapes for {name}: "
                           f"all dimensions except the first must match")
                    break
        elif first.request_type == RequestType.REDUCESCATTER:
            if any(r.tensor_shape != first.tensor_shape for r in reqs):
                err = (f"Mismatched reducescatter tensor shapes for "
                       f"{name}: "
                       + ", ".join(sorted({str(r.tensor_shape)
                                           for r in reqs})))
            elif any(r.reduce_op != first.reduce_op for r in reqs):
                err = f"Mismatched reduce ops for tensor {name}"
            elif first.reduce_op == ReduceOp.ADASUM:
                err = (f"Adasum is not defined for reducescatter "
                       f"(tensor {name})")

        if err is not None:
            return Response(response_type=ResponseType.ERROR,
                            tensor_names=[name], error_message=err)

        resp = Response(
            response_type=ResponseType(int(first.request_type)),
            tensor_names=[name],
            tensor_type=first.tensor_type,
            devices=[first.device],
            process_set_id=first.process_set_id,
        )
        if first.request_type == RequestType.ALLREDUCE:
            resp.tensor_sizes = [first.tensor_shape.num_elements]
            resp.reduce_op = first.reduce_op
            resp.prescale_factor = first.prescale_factor
            resp.postscale_factor = first.postscale_factor
            # Negotiated dims ride the response so cache parameters stay
            # coherent on every rank (incl. joined ranks' stand-ins).
            resp.tensor_shapes = [first.tensor_shape]
        elif first.request_type == RequestType.ALLGATHER:
            # First-dim size per rank, in rank order (0 for joined
            # ranks); for a process set, per member in member order.
            by_rank = {r.request_rank: r for r in reqs}
            if first.process_set_id:
                from horovod_tpu import process_sets

                members = process_sets.ranks_of(first.process_set_id)
                if members is None:
                    return Response(
                        response_type=ResponseType.ERROR,
                        tensor_names=[name],
                        error_message=(
                            f"process set {first.process_set_id} is not "
                            "registered on the coordinator (construct "
                            "the ProcessSet on every rank)"))
                order = members
            else:
                order = range(self.size)
            resp.tensor_sizes = [
                by_rank[r].tensor_shape.dims[0] if r in by_rank else 0
                for r in order]
        elif first.request_type == RequestType.BROADCAST:
            resp.tensor_sizes = [first.root_rank]
        elif first.request_type == RequestType.REDUCESCATTER:
            resp.tensor_sizes = [first.tensor_shape.num_elements]
            resp.reduce_op = first.reduce_op
            resp.tensor_shapes = [first.tensor_shape]
        return resp

    # -- fusion (parity: FuseResponses, controller.cc:638-759) -----------

    def _fuse_responses(self, responses: List[Response]) -> List[Response]:
        out: List[Response] = []
        pending: Optional[Response] = None
        pending_bytes = 0
        for r in responses:
            fusable = (r.response_type == ResponseType.ALLREDUCE
                       and not r.error_message)
            if not fusable:
                if pending is not None:
                    out.append(pending)
                    pending = None
                out.append(r)
                continue
            nbytes = sum(r.tensor_sizes) * r.tensor_type.itemsize
            if pending is not None and \
                    pending.tensor_type == r.tensor_type and \
                    pending.devices == r.devices and \
                    pending.reduce_op == r.reduce_op and \
                    pending.prescale_factor == r.prescale_factor and \
                    pending.postscale_factor == r.postscale_factor and \
                    pending.process_set_id == r.process_set_id and \
                    pending_bytes + nbytes <= self.fusion_threshold:
                pending.tensor_names.extend(r.tensor_names)
                pending.tensor_sizes.extend(r.tensor_sizes)
                pending.tensor_shapes.extend(r.tensor_shapes)
                pending_bytes += nbytes
            else:
                if pending is not None:
                    out.append(pending)
                pending = r
                pending_bytes = nbytes
        if pending is not None:
            out.append(pending)
        return out

    # -- execution -------------------------------------------------------

    def _get_entries(self, resp: Response) -> List[TensorTableEntry]:
        """Fetch (or zero-allocate, when joined) the entries of a response.
        Parity: GetTensorEntriesFromResponse (tensor_queue.cc:72-117)."""
        entries = []
        with self._queue_lock:
            for i, nm in enumerate(resp.tensor_names):
                if nm in self._table:
                    entries.append(self._table.pop(nm))
                else:
                    # This rank joined: allocate a zero stand-in.
                    dt = _np_dtype(resp.tensor_type)
                    if resp.response_type == ResponseType.ALLREDUCE:
                        n = resp.tensor_sizes[i]
                        arr = np.zeros(n, dt)
                    elif resp.response_type == ResponseType.REDUCESCATTER:
                        # Needs the negotiated shape — the scatter splits
                        # over dim 0, so a flat stand-in would desync the
                        # ring chunk boundaries.
                        arr = np.zeros(
                            tuple(resp.tensor_shapes[i].dims), dt)
                    elif resp.response_type == ResponseType.ALLGATHER:
                        arr = np.zeros(0, dt)
                    else:
                        arr = np.zeros(0, dt)
                    req = Request(request_rank=self.rank,
                                  tensor_name=nm,
                                  tensor_type=resp.tensor_type,
                                  tensor_shape=TensorShape(arr.shape))
                    entries.append(
                        TensorTableEntry(nm, arr, -1, req))
        return entries

    def _perform_operation(self, resp: Response,
                           from_cache: bool = False) -> None:
        from horovod_tpu.ops import cpu_backend

        if resp.process_set_id and \
                resp.response_type != ResponseType.ERROR:
            # Process-set responses reach every rank in the response
            # stream; non-members simply skip (members always have the
            # entries — join is global-set-only, so no stand-ins here).
            from horovod_tpu import process_sets

            members = process_sets.ranks_of(resp.process_set_id)
            if members is None or self.rank not in members:
                return

        if resp.response_type == ResponseType.JOIN:
            self._last_joined_rank = int(resp.tensor_sizes[0]) \
                if resp.tensor_sizes else -1
            with self._queue_lock:
                jh, self._join_handle = self._join_handle, None
                self._joined = False
            if jh is not None:
                self.handles.mark_done(jh, Status.ok(), None)
            return

        if resp.response_type == ResponseType.EVICT:
            ranks = [int(x) for x in resp.tensor_sizes]
            blackbox_mod.note("evict", time.monotonic_ns(),
                              ranks=ranks)
            if self.rank in ranks:
                # The coordinator declared *us* dead (e.g. a long GC
                # pause): the group has moved on without this rank, so
                # rejoining is impossible — stop before desyncing it.
                blackbox_mod.dump("evicted",
                                  "declared dead by the coordinator")
                raise RuntimeError(
                    "evicted by the coordinator (missed heartbeats)")
            self._evicted_ranks.update(ranks)
            self._ranks_failed = sorted(
                set(self._ranks_failed) | set(ranks))
            self.log.error(
                "rank(s) %s evicted; completing in-flight collectives "
                "on the survivors", ranks)
            blackbox_mod.dump("ranks_failed", f"evicted={ranks}")
            return

        if resp.response_type == ResponseType.ERROR:
            for nm in resp.tensor_names:
                entries = self._get_entries(
                    Response(response_type=ResponseType.ERROR,
                             tensor_names=[nm]))
                for e in entries:
                    self._release_name(e.name)
                    if e.handle >= 0:
                        self.handles.mark_done(
                            e.handle,
                            Status.precondition_error(resp.error_message),
                            None)
            return

        if not from_cache:
            # Populate the response cache BEFORE execution and regardless
            # of local execution status: the put stores metadata only, and
            # doing it unconditionally in response-stream order is what
            # keeps every rank's cache (positions, LRU, evictions)
            # coherent even if one rank's data plane hiccups.
            self._cache.put(resp)

        entries = self._get_entries(resp)
        op_name = resp.response_type.name
        self.timeline.start(resp.tensor_names[0], op_name)
        tracer = self._tracer
        if tracer is not None:
            # One collective seq per executed response: responses run
            # serially in response-stream order, identically on every
            # rank, so the counter needs no wire traffic to agree.
            seq = tracer.begin_collective()
            t_exec0 = time.monotonic_ns()
            first_enq = min((e.enqueue_ns for e in entries
                             if e.handle >= 0), default=0)
            if first_enq:
                # Negotiation latency: first local enqueue -> execution.
                tracer.span("negotiate", first_enq, t_exec0, seq=seq,
                            name=resp.tensor_names[0], op=op_name,
                            tensors=len(entries))
        deadline_on = self.collective_timeout > 0
        if deadline_on:
            # Busy marker for probe acks: the recv thread reads it to
            # tell the coordinator we are inside a collective (and for
            # how long) even while this thread is blocked in the ring.
            self._in_collective_name = resp.tensor_names[0]
            self._in_collective_since = time.monotonic()
        bb = self._blackbox
        if bb is not None:
            # Flight-recorder begin record: O(1) append, reusing a
            # timestamp an enabled layer already took (tracer read or
            # deadline marker) — never a fresh clock read.
            self._blackbox_seq += 1
            bb_t0 = (t_exec0 if tracer is not None
                     else int(self._in_collective_since * 1e9)
                     if deadline_on else 0)
            peer = (self.rank - 1) % self.size if self.size > 1 else -1
            tp = getattr(self._transports.get(peer), "kind", "")
            bb.collective_begin(
                bb_t0, self._blackbox_seq, resp.tensor_names[0],
                op_name,
                sum(getattr(e.array, "nbytes", 0) or 0
                    for e in entries),
                peer, tp)
        try:
            if resp.response_type == ResponseType.ALLREDUCE:
                results = cpu_backend.allreduce(self, entries, resp)
            elif resp.response_type == ResponseType.ALLGATHER:
                results = cpu_backend.allgather(self, entries, resp)
            elif resp.response_type == ResponseType.BROADCAST:
                results = cpu_backend.broadcast(self, entries, resp)
            elif resp.response_type == ResponseType.ALLTOALL:
                results = cpu_backend.alltoall(self, entries, resp)
            elif resp.response_type == ResponseType.REDUCESCATTER:
                results = cpu_backend.reducescatter(self, entries, resp)
            elif resp.response_type == ResponseType.BARRIER:
                cpu_backend.barrier(self, resp)
                results = [None] * len(entries)
            else:
                raise RuntimeError(f"bad response type {resp.response_type}")
            status = Status.ok()
        except cpu_backend.HopTimeout as e:
            results = [None] * len(entries)
            if deadline_on:
                self._in_collective_since = 0.0
                status = self._collective_abort(resp, entries, e)
            else:
                # The always-on send-wait backstop tripped with the
                # deadline knob off: surface it like any other
                # data-plane failure (no abort agreement to run).
                self.log.error("collective %s failed: %r", op_name, e)
                status = Status.unknown_error(str(e))
        except wire.WireCorruptionError as e:
            # The recovery ladder exhausted every rung on a link
            # (retransmit budget, reconnect window, failover) — the
            # bottom rung is the exact PR-6 gang-wide abort/evict/replay
            # a hop deadline takes (docs/fault_tolerance.md).
            results = [None] * len(entries)
            blackbox_mod.note("wire.corruption", time.monotonic_ns(),
                              peer=int(getattr(e, "peer", -1)),
                              cause=str(getattr(e, "cause", "")))
            if deadline_on:
                self._in_collective_since = 0.0
                status = self._collective_abort(resp, entries, e)
            else:
                self.log.error("collective %s failed: %r", op_name, e)
                status = Status.unknown_error(str(e))
                blackbox_mod.dump("wire_corruption", str(e))
        except Exception as e:
            self.log.error("collective %s failed: %r", op_name, e)
            results = [None] * len(entries)
            status = Status.unknown_error(str(e))
        if deadline_on:
            self._in_collective_since = 0.0
        if bb is not None:
            # End record closes the in-flight marker.  Untimed on the
            # happy path (no extra clock read when nothing fails); a
            # failed collective may read the clock freely.
            bb.collective_end(
                0 if status.ok_() else time.monotonic_ns(),
                self._blackbox_seq, status.ok_())
        self.timeline.end(resp.tensor_names[0])
        if tracer is not None:
            t_cb0 = time.monotonic_ns()
        for e, res in zip(entries, results):
            self._release_name(e.name)
            if e.handle >= 0:
                self.handles.mark_done(e.handle, status, res)
        if tracer is not None:
            t_end = time.monotonic_ns()
            tracer.span("callback", t_cb0, t_end, seq=seq,
                        tensors=len(entries))
            # Envelope span: contains pack/hop/unpack/callback in the
            # merged view; "negotiate" precedes it on the same seq.
            tracer.span("collective", t_exec0, t_end, seq=seq,
                        name=resp.tensor_names[0], op=op_name,
                        ok=status.ok_())

    def cache_stats(self) -> Dict[str, int]:
        return self._cache.stats()

    def _abort(self, reason: str, exc: Optional[BaseException] = None
               ) -> None:
        self._aborted = True
        # Recorded for the elastic wrapper: a lost-coordinator abort on a
        # worker means rank 0 failed, which re-forms instead of exiting.
        self._abort_reason = reason
        # Typed aborts (FencedError, ...) keep their class all the way
        # to the training loop: pending handles and the next submission
        # re-raise THIS object instead of a bare RuntimeError.
        self._abort_exc = exc
        blackbox_mod.dump("engine_abort", reason)
        self._shutdown_flag.set()
