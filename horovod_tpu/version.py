"""Version of the horovod_tpu framework.

Capability target: Horovod v0.19.1 (reference: /root/reference,
``horovod/__init__.py:1``) rebuilt TPU-native.
"""

__version__ = "0.1.0"
