"""Keras front-end: ``import horovod_tpu.keras as hvd``.

Role parity: ``horovod/keras/__init__.py`` + ``horovod/_keras`` — the
Keras training surface: ``DistributedOptimizer`` (gradient allreduce
before apply), broadcast/metric/LR-warmup callbacks, and ``load_model``
that rewraps the optimizer.  Built for Keras 3; with the TF backend the
collectives run through the same ``tf.py_function`` bridge as the
TensorFlow front-end.
"""

from __future__ import annotations

import keras

from horovod_tpu.basics import (  # noqa: F401
    cache_stats,
    cross_rank,
    cross_size,
    cuda_built,
    gloo_built,
    init,
    is_homogeneous,
    is_initialized,
    local_rank,
    local_size,
    mpi_built,
    mpi_enabled,
    mpi_threads_supported,
    nccl_built,
    rank,
    rocm_built,
    shutdown,
    size,
    xla_built,
)
from horovod_tpu.common.types import ReduceOp
from horovod_tpu.keras import callbacks  # noqa: F401
from horovod_tpu.keras.callbacks import (  # noqa: F401
    BroadcastGlobalVariablesCallback,
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
)

Average = ReduceOp.AVERAGE
Sum = ReduceOp.SUM
Adasum = ReduceOp.ADASUM


def _tf_surface():
    from horovod_tpu import tensorflow as hvd_tf

    return hvd_tf


def allreduce(value, name=None, average=True):
    """Eager allreduce of a numpy/backend tensor (keras surface parity:
    keras/__init__.py allreduce)."""
    from horovod_tpu.ops import eager

    import numpy as np

    return eager.allreduce(np.asarray(value), average=average, name=name)


def allgather(value, name=None):
    from horovod_tpu.ops import eager

    import numpy as np

    return eager.allgather(np.asarray(value), name=name)


def broadcast(value, root_rank=0, name=None):
    from horovod_tpu.ops import eager

    import numpy as np

    return eager.broadcast(np.asarray(value), root_rank=root_rank,
                           name=name)


def DistributedOptimizer(optimizer, name=None,
                         device_dense="", device_sparse="",
                         compression=None, op=ReduceOp.AVERAGE,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=False,
                         process_set=None):
    """Wraps a Keras optimizer so gradients are allreduced across ranks
    before being applied (parity: _keras/__init__.py:20-86 — dynamic
    subclass overriding the gradient-aggregation step).

    ``backward_passes_per_step=N`` aggregates gradients locally over N
    ``apply_gradients`` calls and allreduces+applies only on the Nth
    (intermediate calls leave variables untouched);
    ``average_aggregated_gradients=True`` divides the local sum by N
    before the allreduce — both exactly as on the TF surface
    (``horovod_tpu.tensorflow.DistributedOptimizer``).

    Supported with the TensorFlow Keras backend, whose trainer funnels
    through ``apply_gradients``.  The JAX and torch Keras backends
    bypass ``apply_gradients`` (``stateless_apply`` / ``apply``), so
    wrapping there would silently skip gradient synchronization — use
    the native ``horovod_tpu`` (JAX) or ``horovod_tpu.torch`` front-ends
    for those stacks instead."""
    backend = keras.backend.backend()
    if backend != "tensorflow":
        raise NotImplementedError(
            f"horovod_tpu.keras.DistributedOptimizer supports the "
            f"tensorflow Keras backend; the current backend is "
            f"'{backend}', whose trainer does not route through "
            f"apply_gradients. Use horovod_tpu (JAX) or "
            f"horovod_tpu.torch directly.")
    hvd_tf = _tf_surface()
    comp = compression or hvd_tf.Compression.none
    return hvd_tf.DistributedOptimizer(
        optimizer, name=name, compression=comp, op=op,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        process_set=process_set)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=None):
    """Loads a Keras model and wraps its optimizer in
    ``DistributedOptimizer`` (parity: keras/__init__.py:117-148)."""
    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects)
    if getattr(model, "optimizer", None) is not None:
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model


def __getattr__(name):
    # ``Compression`` must be the TF-surface compressor (it handles
    # tf.Tensors; the base ops.compression one is numpy/JAX and crashes
    # on them) — resolved lazily so importing this module stays valid
    # on non-TF Keras backends.  Parity: reference keras/__init__.py:28.
    if name == "Compression":
        return _tf_surface().Compression
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
