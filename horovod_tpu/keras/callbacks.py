"""Keras callbacks for distributed training.

Role parity: ``horovod/_keras/callbacks.py`` — broadcast initial state,
average metrics across ranks at epoch end, and learning-rate
warmup/schedule callbacks that scale with the number of workers.
Implemented against Keras 3 (framework-agnostic weight access via
get_weights/set_weights numpy arrays, so the same callbacks serve the
TF, JAX, and torch Keras backends).
"""

from __future__ import annotations

import keras
import numpy as np

from horovod_tpu import basics
from horovod_tpu.ops import eager as _eager


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcasts model (and optimizer) state from root at the start of
    training, so random initializations agree (parity:
    _keras/callbacks.py:20-43)."""

    def __init__(self, root_rank=0):
        super().__init__()
        self.root_rank = root_rank
        self._model_done = False
        self._opt_done = False

    def on_batch_begin(self, batch, logs=None):
        if basics.size() <= 1:
            return
        if not self._model_done:
            weights = self.model.get_weights()
            handles = [_eager.broadcast_async(w, self.root_rank,
                                              name=f"kbc.model.{i}")
                       for i, w in enumerate(weights)]
            self.model.set_weights(
                [_eager.synchronize(h) for h in handles])
            self._model_done = True
        if not self._opt_done:
            # Keras 3 builds optimizer variables lazily inside the first
            # apply, so the state broadcast waits until they exist
            # (typically the second batch) instead of latching early.
            opt = getattr(self.model, "optimizer", None)
            ovars = list(getattr(opt, "variables", None) or [])
            if ovars:
                handles = [
                    _eager.broadcast_async(np.asarray(v), self.root_rank,
                                           name=f"kbc.opt.{i}")
                    for i, v in enumerate(ovars)]
                for v, h in zip(ovars, handles):
                    out = np.asarray(_eager.synchronize(h))
                    # the engine flattens 0-d scalars to shape (1,)
                    v.assign(out.reshape(np.asarray(v).shape))
                self._opt_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Averages epoch-end metrics over all ranks so rank-0 logging and
    checkpoint decisions reflect the whole job (parity:
    _keras/callbacks.py:46-84)."""

    def on_epoch_end(self, epoch, logs=None):
        if logs is None or basics.size() <= 1:
            return
        for key in sorted(logs.keys()):
            value = logs[key]
            if isinstance(value, (int, float, np.floating, np.integer)):
                logs[key] = float(_eager.allreduce(
                    np.asarray(value, np.float64),
                    op=_eager.ReduceOp.AVERAGE,
                    name=f"metric.{epoch}.{key}"))


def _get_lr(optimizer) -> float:
    return float(np.asarray(optimizer.learning_rate))


def _set_lr(optimizer, lr: float, momentum_correction: bool) -> None:
    # Momentum correction (Goyal et al., the recipe behind
    # _keras/callbacks.py:120-134): when the LR changes old→new, the SGD
    # velocity v (which has the LR folded in: v ← m·v − lr·g) must be
    # rescaled by new/old.  The reference does it by scaling the momentum
    # *coefficient* for exactly the next update and restoring it
    # afterwards:  v' = (m·new/old)·v − new·g.  Here we rescale the
    # momentum *buffers* once at the change instead:  v ← (new/old)·v,
    # then v' = m·v − new·g — algebraically identical, including under a
    # per-batch warmup ramp (each change applies its own old/new ratio
    # exactly once).  This intentional divergence exists because in
    # Keras 3 ``optimizer.momentum`` is a plain Python float baked into
    # the compiled update step — mutating it between batches does not
    # reliably take effect — while the velocity slots
    # (``optimizer.momentums``) are real variables whose assignment
    # always does.
    old = _get_lr(optimizer)
    optimizer.learning_rate = lr
    if momentum_correction and old > 0 and lr != old and \
            getattr(optimizer, "momentums", None):
        scale = lr / old
        for m in optimizer.momentums:
            m.assign(m * scale)


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiplies the initial LR by ``multiplier`` inside
    [start_epoch, end_epoch) — multiplier is a constant or a function of
    epoch; ``staircase`` applies per epoch, else per batch with epoch
    fractions (parity: _keras/callbacks.py:87-159)."""

    def __init__(self, multiplier, start_epoch=0, end_epoch=None,
                 staircase=True, momentum_correction=True,
                 steps_per_epoch=None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr = None
        self.current_epoch = None
        if not callable(multiplier):
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier

    def _in_range(self, epoch) -> bool:
        return (epoch >= self.start_epoch and
                (self.end_epoch is None or epoch < self.end_epoch))

    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = _get_lr(self.model.optimizer)
        if not self.staircase and not self.steps_per_epoch:
            raise ValueError(
                "steps_per_epoch is required when staircase=False")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase and self._in_range(epoch):
            _set_lr(self.model.optimizer,
                    self.initial_lr * self.multiplier(epoch),
                    self.momentum_correction)
        elif not self.staircase and self.end_epoch is not None and \
                epoch == self.end_epoch:
            # Batch fractions stop just short of end_epoch; land exactly
            # on the final value when the ramp completes.
            _set_lr(self.model.optimizer,
                    self.initial_lr * self.multiplier(self.end_epoch),
                    self.momentum_correction)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase and self._in_range(self.current_epoch):
            epoch = self.current_epoch + float(batch) / self.steps_per_epoch
            _set_lr(self.model.optimizer,
                    self.initial_lr * self.multiplier(epoch),
                    self.momentum_correction)

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = _get_lr(self.model.optimizer)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup, the "facebook 1-hour" recipe (parity:
    _keras/callbacks.py:162-200).  The optimizer's configured LR is the
    already-size-scaled target; the ramp starts at target/size() and
    reaches the target after ``warmup_epochs``:
    lr(epoch) = target * (epoch * (size-1) / warmup + 1) / size."""

    def __init__(self, warmup_epochs=5, momentum_correction=True,
                 steps_per_epoch=None, verbose=0):
        self.verbose = verbose
        n = basics.size()

        def multiplier(epoch):
            return (epoch * (n - 1) / warmup_epochs + 1) / n

        super().__init__(multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.end_epoch - 1 and self.verbose and \
                basics.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {_get_lr(self.model.optimizer):.6g}.")
