"""Installable operator CLIs (``[project.scripts]`` in pyproject.toml).

- ``hvd-top`` (hvd_top.py): live terminal dashboard over the gang
  aggregator's ``/gang/metrics.json`` view.
- ``hvd-trace`` (hvd_trace.py): merge/analyze/diff gang-wide span
  traces.
- ``hvd-postmortem`` (hvd_postmortem.py): gang-correlated verdict over
  flight-recorder dumps.

The repo-root ``tools/`` directory keeps thin shims for the historical
``python tools/<name>.py`` invocations (and for the lints that live
there, which are dev-only and not installed).
"""
