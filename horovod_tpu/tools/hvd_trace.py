#!/usr/bin/env python3
"""Merge, analyze, and diff gang-wide trace files.

Input is the per-rank JSONL span streams written by
horovod_tpu/telemetry/trace.py (``HVD_TRACE=1``; one
``trace_rank<R>.jsonl`` per rank under ``HVD_TRACE_DIR``).  See
docs/timeline.md "Gang-wide tracing" for the workflow.

Subcommands:

* ``merge <out.json> <trace_rank*.jsonl ...>`` — align every rank's
  monotonic clock onto rank 0's axis (median of the midpoint-method
  ``clock`` records; wall-anchor fallback when a stream carries none)
  and fuse the streams into one Chrome/Perfetto ``traceEvents`` JSON —
  load it at https://ui.perfetto.dev or chrome://tracing.
* ``analyze <trace_rank*.jsonl ...>`` — per-collective critical path:
  for each fused collective (grouped by ``seq``, identical on every
  rank), which (rank, phase, hop) span bounded it, plus a mean
  per-phase breakdown across the run.
* ``diff <base> <new>`` — attribute a regression between two traced
  runs (directories of rank files, or two ``analyze --json`` outputs)
  to specific phases: prints the top phase deltas.

Importable: bench.py uses :func:`analyze_dir` to embed a
``phase_breakdown`` block into its snapshots, and
tools/check_bench_regression.py uses :func:`top_deltas` to name the
phase that moved when its throughput gate trips.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

# Span phases that belong to a fused collective's execution window and
# compete for its critical path (negotiate overlaps the previous
# collective, callback is serial bookkeeping — both reported in the
# breakdown, but hop/pack/unpack are what bound the data plane).
_CRITICAL_PHASES = ("hop", "pack", "unpack")
_BREAKDOWN_PHASES = ("negotiate", "pack", "hop.recv", "hop.reduce",
                     "hop.send_wait", "unpack", "callback")


# -- loading ------------------------------------------------------------


def _rank_from_name(path: str) -> int:
    m = re.search(r"trace_rank(\d+)\.jsonl", os.path.basename(path))
    return int(m.group(1)) if m else -1


def load_rank_file(path: str) -> dict:
    """Parse one rank's JSONL stream.  Corrupt or truncated lines (a
    crash mid-record) are skipped — every intact record still loads."""
    meta: List[dict] = []
    clocks: List[dict] = []
    spans: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail from a crash mid-write
            k = rec.get("k")
            if k == "meta":
                meta.append(rec)
            elif k == "clock":
                clocks.append(rec)
            elif k == "span":
                spans.append(rec)
    rank = meta[-1]["rank"] if meta else _rank_from_name(path)
    return {"path": path, "rank": rank, "meta": meta,
            "clocks": clocks, "spans": spans}


def trace_files(d: str) -> List[str]:
    return sorted(glob.glob(os.path.join(d, "trace_rank*.jsonl")),
                  key=_rank_from_name)


def load_files(paths: List[str]) -> List[dict]:
    return [load_rank_file(p) for p in paths]


# -- clock alignment ----------------------------------------------------


def rank_offsets(files: List[dict]) -> Dict[int, int]:
    """Per-rank offset (ns) mapping each rank's monotonic axis onto the
    reference rank's (rank 0 when present): the median of the rank's
    midpoint-method clock records.  A stream with no clock records
    falls back to the wall-anchor difference — NTP-grade, still exact
    for same-host ranks sharing one system CLOCK_MONOTONIC."""
    by_rank = {f["rank"]: f for f in files}
    ref = by_rank.get(0) or by_rank[min(by_rank)]
    offsets: Dict[int, int] = {}
    for r, f in sorted(by_rank.items()):
        if f is ref:
            offsets[r] = 0
            continue
        offs = sorted(c["offset_ns"] for c in f["clocks"])
        if offs:
            offsets[r] = offs[len(offs) // 2]
        elif f["meta"] and ref["meta"]:
            m, m0 = f["meta"][0], ref["meta"][0]
            offsets[r] = ((m["wall_anchor_ns"] - m["mono_anchor_ns"])
                          - (m0["wall_anchor_ns"] - m0["mono_anchor_ns"]))
        else:
            offsets[r] = 0
    return offsets


# -- merge --------------------------------------------------------------


def merge(files: List[dict]) -> dict:
    """Fuse per-rank streams into one Chrome/Perfetto trace: one process
    per rank, timestamps aligned onto the reference rank's clock."""
    offsets = rank_offsets(files)
    events: List[dict] = []
    for f in sorted(files, key=lambda x: x["rank"]):
        r = f["rank"]
        off = offsets[r]
        events.append({"ph": "M", "pid": r, "tid": 0,
                       "name": "process_name",
                       "args": {"name": f"rank {r}"}})
        for s in f["spans"]:
            args = {k: v for k, v in s.items()
                    if k not in ("k", "ph", "t0", "t1")}
            ts_us = (s["t0"] + off) / 1e3
            if s["t1"] == s["t0"]:
                events.append({"name": s["ph"], "ph": "i", "pid": r,
                               "tid": 0, "ts": ts_us, "s": "p",
                               "args": args})
            else:
                events.append({"name": s["ph"], "ph": "X", "pid": r,
                               "tid": 0, "ts": ts_us,
                               "dur": (s["t1"] - s["t0"]) / 1e3,
                               "args": args})
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- analyze ------------------------------------------------------------


def _hop_subphase(s: dict) -> str:
    """Refine a hop span to its dominant sub-timing."""
    parts = {"hop.recv": s.get("recv_ns", 0),
             "hop.reduce": s.get("reduce_ns", 0),
             "hop.send_wait": s.get("send_wait_ns", 0)}
    return max(parts, key=parts.get)


def _stall_end_ns(s: dict, off: int) -> int:
    """Aligned time at which the span's blocking wait resolved.  For a
    hop that is the end of receive+reduce (the moment the chunk could
    leave for the next rank), not the span end — the span also covers
    the send fence, so a downstream echo can end *before* its origin."""
    if s["ph"] == "hop":
        return s["t0"] + s.get("recv_ns", 0) + s.get("reduce_ns", 0) + off
    return s["t1"] + off


def analyze(files: List[dict]) -> dict:
    """Per-collective critical path + mean per-phase breakdown.

    Collectives are grouped by ``seq`` (bumped identically on every
    rank, in response-stream order).  The critical span of a collective
    is the longest hop/pack/unpack span any rank recorded for that seq
    — the data-plane step the fused op could not finish before; hop
    spans are refined to hop.recv / hop.reduce / hop.send_wait by their
    largest sub-timing.  ``phase_breakdown_ms`` is mean milliseconds
    per collective per rank, the block bench.py embeds in snapshots."""
    offsets = rank_offsets(files)
    groups: Dict[int, list] = {}
    names: Dict[int, dict] = {}
    totals = {ph: 0.0 for ph in _BREAKDOWN_PHASES}
    for f in files:
        off = offsets[f["rank"]]
        for s in f["spans"]:
            seq = s.get("seq", -1)
            ph = s["ph"]
            if ph == "hop":
                totals["hop.recv"] += s.get("recv_ns", 0) / 1e6
                totals["hop.reduce"] += s.get("reduce_ns", 0) / 1e6
                totals["hop.send_wait"] += s.get("send_wait_ns", 0) / 1e6
            elif ph in totals:
                totals[ph] += (s["t1"] - s["t0"]) / 1e6
            if seq < 0:
                continue
            if ph == "collective":
                names.setdefault(seq, {"name": s.get("name", "?"),
                                       "op": s.get("op", "?")})
                groups.setdefault(seq, [])
            if ph in _CRITICAL_PHASES or ph == "collective":
                groups.setdefault(seq, []).append((f["rank"], off, s))
    collectives = []
    for seq in sorted(groups):
        spans = groups[seq]
        coll = [(r, off, s) for r, off, s in spans
                if s["ph"] == "collective"]
        wall_ms = 0.0
        if coll:
            wall_ms = (max(s["t1"] + off for _, off, s in coll)
                       - min(s["t0"] + off for _, off, s in coll)) / 1e6
        # Critical span: longest hop/pack/unpack span — but a stalled
        # hop *propagates*: every downstream rank blocks nearly as long
        # waiting on the late chunk, and each echo span is marginally
        # longer than the origin (it also absorbs the origin's combine
        # and wire time).  Among near-tied longest spans, the origin is
        # the one whose blocking wait RESOLVED earliest: data cannot
        # reach an echo before the origin finished receiving+reducing.
        cand = [(s["t1"] - s["t0"], r, off, s) for r, off, s in spans
                if s["ph"] in _CRITICAL_PHASES]
        crit = None
        if cand:
            dmax = max(d for d, _, _, _ in cand)
            tied = [c for c in cand if c[0] >= 0.8 * dmax]
            crit = min(tied, key=lambda c: _stall_end_ns(c[3], c[2]))
        entry = dict(seq=seq, wall_ms=round(wall_ms, 3),
                     **names.get(seq, {"name": "?", "op": "?"}))
        if crit is not None:
            dur, r, _, s = crit
            phase = _hop_subphase(s) if s["ph"] == "hop" else s["ph"]
            entry["critical"] = {
                "rank": r, "phase": phase, "dur_ms": round(dur / 1e6, 3),
                "hop": s.get("hop", -1), "peer": s.get("peer", -1),
                "ring": s.get("ring", ""), "tp": s.get("tp", "")}
        collectives.append(entry)
    n = max(1, len(collectives)) * max(1, len(files))
    breakdown = {ph: round(totals[ph] / n, 4)
                 for ph in _BREAKDOWN_PHASES}
    return {"num_ranks": len(files),
            "num_collectives": len(collectives),
            "clock_offsets_ns": {str(r): o for r, o in offsets.items()},
            "phase_breakdown_ms": breakdown,
            "collectives": collectives}


def analyze_dir(d: str) -> Optional[dict]:
    """:func:`analyze` over every rank file in a trace dir (None when
    the dir holds no trace files) — the bench.py entry point."""
    paths = trace_files(d)
    if not paths:
        return None
    return analyze(load_files(paths))


# -- diff ---------------------------------------------------------------


def top_deltas(old: Dict[str, float], new: Dict[str, float],
               top: int = 3) -> List[tuple]:
    """Rank phases by absolute per-collective time moved between two
    ``phase_breakdown_ms`` blocks: [(phase, old_ms, new_ms, delta_ms)],
    largest mover first."""
    rows = []
    for ph in sorted(set(old) | set(new)):
        a = float(old.get(ph, 0.0))
        b = float(new.get(ph, 0.0))
        rows.append((ph, a, b, b - a))
    rows.sort(key=lambda x: abs(x[3]), reverse=True)
    return rows[:top]


def _load_breakdown(path: str) -> Dict[str, float]:
    """A diff operand: a trace dir, a rank file, or an ``analyze
    --json`` / bench-snapshot JSON carrying ``phase_breakdown_ms``."""
    if os.path.isdir(path):
        rep = analyze_dir(path)
        if rep is None:
            raise SystemExit(f"no trace_rank*.jsonl under {path}")
        return rep["phase_breakdown_ms"]
    if path.endswith(".jsonl"):
        return analyze(load_files([path]))["phase_breakdown_ms"]
    with open(path) as fh:
        doc = json.load(fh)
    for key in ("phase_breakdown_ms", "phase_breakdown"):
        if key in doc:
            blk = doc[key]
            return blk.get("phase_breakdown_ms", blk) \
                if isinstance(blk, dict) and "phase_breakdown_ms" in blk \
                else blk
    raise SystemExit(f"{path}: no phase_breakdown_ms block")


# -- CLI ----------------------------------------------------------------


def _print_analysis(rep: dict) -> None:
    print(f"ranks: {rep['num_ranks']}  "
          f"collectives: {rep['num_collectives']}")
    offs = rep["clock_offsets_ns"]
    print("clock offsets vs rank 0 (us): "
          + "  ".join(f"r{r}:{int(o) / 1e3:+.1f}"
                      for r, o in sorted(offs.items(),
                                         key=lambda kv: int(kv[0]))))
    print("phase breakdown (mean ms per collective per rank):")
    for ph, ms in rep["phase_breakdown_ms"].items():
        print(f"  {ph:<14} {ms:9.4f}")
    crit_count: Dict[str, int] = {}
    for c in rep["collectives"]:
        crit = c.get("critical")
        if not crit:
            continue
        key = f"rank {crit['rank']} {crit['phase']}"
        crit_count[key] = crit_count.get(key, 0) + 1
    if crit_count:
        print("critical path (collectives bounded, by rank+phase):")
        for key, n in sorted(crit_count.items(),
                             key=lambda kv: -kv[1]):
            print(f"  {key:<24} {n}")
    slowest = sorted((c for c in rep["collectives"] if c.get("critical")),
                     key=lambda c: -c["wall_ms"])[:5]
    if slowest:
        print("slowest collectives:")
        for c in slowest:
            cr = c["critical"]
            where = f"hop {cr['hop']} peer {cr['peer']}" \
                if cr["phase"].startswith("hop") else cr["phase"]
            print(f"  seq {c['seq']:>4} {c['op']:<12} "
                  f"wall {c['wall_ms']:8.3f} ms  <- rank {cr['rank']} "
                  f"{cr['phase']} ({where}, {cr['dur_ms']:.3f} ms)")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hvd_trace.py",
        description="merge / analyze / diff gang-wide trace files")
    sub = ap.add_subparsers(dest="cmd", required=True)

    mp = sub.add_parser("merge", help="fuse rank files into one "
                        "Chrome/Perfetto trace JSON")
    mp.add_argument("out")
    mp.add_argument("ranks", nargs="+",
                    help="trace_rank*.jsonl files (or one trace dir)")

    an = sub.add_parser("analyze", help="critical path + phase breakdown")
    an.add_argument("ranks", nargs="+")
    an.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")

    df = sub.add_parser("diff", help="attribute a regression between two "
                        "traced runs to phases")
    df.add_argument("base", help="trace dir / rank file / analysis JSON")
    df.add_argument("new")
    df.add_argument("--top", type=int, default=3)

    args = ap.parse_args(argv)

    if args.cmd in ("merge", "analyze"):
        paths: List[str] = []
        for p in args.ranks:
            paths.extend(trace_files(p) if os.path.isdir(p) else [p])
        if not paths:
            print("no trace files", file=sys.stderr)
            return 2
        files = load_files(paths)

    if args.cmd == "merge":
        doc = merge(files)
        with open(args.out, "w") as fh:
            json.dump(doc, fh)
        print(f"wrote {args.out}: {len(doc['traceEvents'])} events "
              f"from {len(files)} rank(s)")
        return 0

    if args.cmd == "analyze":
        rep = analyze(files)
        if args.json:
            json.dump(rep, sys.stdout, indent=1)
            print()
        else:
            _print_analysis(rep)
        return 0

    # diff
    old = _load_breakdown(args.base)
    new = _load_breakdown(args.new)
    print(f"phase deltas (ms per collective per rank), top {args.top}:")
    for ph, a, b, d in top_deltas(old, new, args.top):
        pct = f" ({d / a * 100.0:+.1f}%)" if a else ""
        print(f"  {ph:<14} {a:9.4f} -> {b:9.4f}  {d:+9.4f}{pct}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
