#!/usr/bin/env python3
"""Gang-correlated postmortem over flight-recorder dumps.

Input is a directory of ``blackbox_rank<r>.json`` dumps (plus the
coordinator-pulled ``blackbox_rank<r>.pulled.json`` copies) written by
horovod_tpu/telemetry/blackbox.py at a terminal failure — the always-on
black box every rank carries (docs/fault_tolerance.md "the black box",
docs/troubleshooting.md "Postmortem workflow").

The verdict names the **first-cause rank**: the rank the rest of the
gang was blocked on, resolved in precedence order:

1. The gang's own ruling — ranks named by ``abort.verdict`` / ``evict``
   events and terminal dump reasons (``evicted``), majority across
   dumps.  The abort agreement already did the hard work; trust it.
2. The most-blamed peer across the survivors' ``collective.timeout``
   blame edges (who each rank was blocked on when its deadline fired).
3. The earliest-silent rank: after aligning each dump's events onto
   rank 0's clock axis (the per-dump midpoint-method offset estimate,
   PR 13's machinery), the rank whose last recorded event is oldest.

What the culprit was doing (phase / peer / seq / collective name) comes
from its own dump when one exists — the coordinator pull fetches a
wedged rank's ring over the still-live control channel even while its
background thread hangs — and from the survivors' blame edges when the
rank died without a trace (SIGKILL).

Usage::

    python tools/hvd_postmortem.py <dump_dir> [--json]

Importable: :func:`analyze` returns the verdict as a dict;
tests/test_blackbox.py drives it end to end.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_NAME_RE = re.compile(r"blackbox_rank(\d+)(\.pulled)?\.json$")

# Dump reasons that mark the dumping rank itself as the failure (vs.
# reasons a healthy survivor records on its way down).
_SELF_FAULT_REASONS = ("evicted",)


# -- loading ------------------------------------------------------------


def load_dump(path: str) -> Optional[dict]:
    """One dump document, or None when torn/corrupt (a crash mid-write
    never happens for the atomic direct dumps, but a pulled copy can
    lose its sender mid-frame)."""
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(doc, dict) or "rank" not in doc:
        return None
    doc["_path"] = path
    doc["_pulled"] = path.endswith(".pulled.json")
    return doc


def dump_files(d: str) -> List[str]:
    out = [p for p in glob.glob(os.path.join(d, "blackbox_rank*.json"))
           if _NAME_RE.search(os.path.basename(p))]
    return sorted(out)


def load_dir(d: str) -> Dict[int, dict]:
    """rank -> dump, preferring a rank's own atomic dump over the
    coordinator-pulled copy (the pull races the direct write; the
    direct file is the complete, reason-stamped document)."""
    by_rank: Dict[int, dict] = {}
    for p in dump_files(d):
        doc = load_dump(p)
        if doc is None:
            continue
        r = int(doc["rank"])
        have = by_rank.get(r)
        if have is None or (have["_pulled"] and not doc["_pulled"]):
            by_rank[r] = doc
    return by_rank


# -- correlation --------------------------------------------------------


def _aligned_last_event_ns(doc: dict) -> int:
    """The dump's newest timed event on rank 0's clock axis (0 = the
    ring holds no timed events)."""
    off = int(doc.get("clock_offset_ns", 0) or 0)
    last = 0
    for ev in doc.get("events", []):
        t = int(ev.get("t_ns", 0) or 0)
        if t:
            last = max(last, t + off)
    return last


def _named_by_gang(dumps: Dict[int, dict]) -> List[int]:
    """Ranks the gang itself ruled against: abort-verdict / evict events
    (majority across dumps) plus any rank whose own dump reason is a
    self-fault (``evicted``)."""
    votes: Dict[int, int] = {}
    for doc in dumps.values():
        named = set()
        for ev in doc.get("events", []):
            if ev.get("kind") in ("abort.verdict", "evict",
                                  "heartbeat.miss", "leader.failover",
                                  "replica.divergence"):
                for r in ev.get("ranks", []) or (
                        [ev["rank"]] if "rank" in ev else []):
                    named.add(int(r))
        for r in named:
            votes[r] = votes.get(r, 0) + 1
    quorum = max(1, (len(dumps) + 1) // 2)
    ruled = sorted(r for r, n in votes.items() if n >= quorum)
    for r, doc in dumps.items():
        if doc.get("reason") in _SELF_FAULT_REASONS and r not in ruled:
            ruled.append(r)
    return sorted(ruled)


def _most_blamed(dumps: Dict[int, dict]) -> Optional[int]:
    """The peer most often named in ``collective.timeout`` blame edges;
    ties go to the lowest rank (same rule the coordinator uses)."""
    blame: Dict[int, int] = {}
    for doc in dumps.values():
        for ev in doc.get("events", []):
            if ev.get("kind") == "collective.timeout":
                peer = int(ev.get("peer", -1))
                if peer >= 0:
                    blame[peer] = blame.get(peer, 0) + 1
    if not blame:
        return None
    top = max(blame.values())
    return min(r for r, n in blame.items() if n == top)


def _earliest_silent(dumps: Dict[int, dict]) -> Optional[int]:
    """The rank that went quiet first on the aligned axis."""
    last: Dict[int, int] = {}
    for r, doc in dumps.items():
        t = _aligned_last_event_ns(doc)
        if t:
            last[r] = t
    if not last:
        return None
    lo = min(last.values())
    return min(r for r, t in last.items() if t == lo)


def _doing(doc: Optional[dict]) -> dict:
    """What a rank was doing per its own dump: the in-flight collective
    (name + begin fields) or its last ``collective.begin``."""
    out = {"name": "", "phase": "", "peer": -1, "seq": -1, "op": ""}
    if doc is None:
        return out
    inf = doc.get("in_flight")
    if isinstance(inf, dict) and inf.get("name"):
        out["name"] = str(inf["name"])
        out["phase"] = "collective"
    for ev in reversed(doc.get("events", [])):
        if ev.get("kind") == "collective.begin" and (
                not out["name"] or ev.get("name") == out["name"]):
            out["name"] = out["name"] or str(ev.get("name", ""))
            out["peer"] = int(ev.get("peer", -1))
            out["seq"] = int(ev.get("seq", -1))
            out["op"] = str(ev.get("op", ""))
            out["phase"] = out["phase"] or "collective"
            break
    return out


def _blamed_doing(dumps: Dict[int, dict], culprit: int) -> dict:
    """Culprit context reconstructed from the survivors' blame edges —
    the fallback when the culprit died without a dump (SIGKILL)."""
    out = {"name": "", "phase": "", "peer": -1, "seq": -1, "op": ""}
    for doc in dumps.values():
        for ev in doc.get("events", []):
            if ev.get("kind") == "collective.timeout" and \
                    int(ev.get("peer", -1)) == culprit:
                out["name"] = str(ev.get("name", ""))
                out["phase"] = str(ev.get("phase", ""))
                return out
    return out


def analyze(d: str) -> Optional[dict]:
    """The gang-correlated verdict for one dump directory, or None when
    it holds no loadable dumps."""
    dumps = load_dir(d)
    if not dumps:
        return None

    ruled = _named_by_gang(dumps)
    blamed = _most_blamed(dumps)
    silent = _earliest_silent(dumps)
    evidence: List[str] = []
    if ruled:
        first_cause = ruled[0]
        evidence.append(
            f"gang ruling: rank(s) {ruled} named by abort/evict "
            f"events across {len(dumps)} dump(s)")
    elif blamed is not None:
        first_cause = blamed
        evidence.append(
            f"blame edges: rank {blamed} is the most-blamed peer in "
            f"collective.timeout records")
    elif silent is not None:
        first_cause = silent
        evidence.append(
            f"clock-aligned silence: rank {silent} stopped recording "
            f"first")
    else:
        first_cause = min(dumps)
        evidence.append(
            "no failure events recorded; defaulting to the lowest "
            "dumped rank")
    if blamed is not None and blamed != first_cause:
        evidence.append(
            f"note: blame edges point at rank {blamed} as well")
    if silent is not None:
        evidence.append(
            f"last aligned activity: rank {silent} is earliest-silent")

    culprit_doc = dumps.get(first_cause)
    doing = _doing(culprit_doc)
    if not doing["name"]:
        doing = _blamed_doing(dumps, first_cause)
    if culprit_doc is None:
        evidence.append(
            f"rank {first_cause} left no dump (died hard); context "
            f"reconstructed from survivors' blame edges")
    elif culprit_doc["_pulled"]:
        evidence.append(
            f"rank {first_cause}'s ring was pulled over the control "
            f"channel by the coordinator (its own dump never landed)")

    ranks = {}
    for r, doc in sorted(dumps.items()):
        blocked = _doing(doc)
        timeout_ev = next(
            (ev for ev in reversed(doc.get("events", []))
             if ev.get("kind") == "collective.timeout"), None)
        if timeout_ev is not None:
            blocked["peer"] = int(timeout_ev.get("peer", blocked["peer"]))
            blocked["phase"] = str(timeout_ev.get("phase",
                                                  blocked["phase"]))
        ranks[r] = {
            "reason": doc.get("reason", ""),
            "pulled": doc["_pulled"],
            "epoch": doc.get("epoch", 0),
            "clock_offset_ns": int(doc.get("clock_offset_ns", 0) or 0),
            "events": len(doc.get("events", [])),
            "blocked_on": blocked,
        }

    return {
        "dir": d,
        "dumped_ranks": sorted(dumps),
        "first_cause": first_cause,
        "doing": doing,
        "gang_ruled": ruled,
        "most_blamed": blamed,
        "earliest_silent": silent,
        "evidence": evidence,
        "ranks": ranks,
    }


# -- CLI ----------------------------------------------------------------


def _print_verdict(v: dict) -> None:
    doing = v["doing"]
    what = doing["name"] or "<unknown collective>"
    extra = []
    if doing["phase"]:
        extra.append(f"phase={doing['phase']}")
    if doing["peer"] >= 0:
        extra.append(f"peer={doing['peer']}")
    if doing["seq"] >= 0:
        extra.append(f"seq={doing['seq']}")
    if doing["op"]:
        extra.append(f"op={doing['op']}")
    print(f"postmortem: {v['dir']}")
    print(f"  first cause: rank {v['first_cause']} — {what}"
          + (f" ({', '.join(extra)})" if extra else ""))
    print("  evidence:")
    for line in v["evidence"]:
        print(f"    - {line}")
    print("  per-rank state at dump time:")
    for r, info in sorted(v["ranks"].items()):
        b = info["blocked_on"]
        on = (f"blocked on peer {b['peer']} in {b['name'] or '<idle>'}"
              if b["peer"] >= 0 else
              (f"in {b['name']}" if b["name"] else "idle"))
        src = "pulled" if info["pulled"] else "direct"
        print(f"    rank {r}: reason={info['reason'] or '-'} {on} "
              f"[{info['events']} events, {src} dump, "
              f"offset {info['clock_offset_ns']} ns]")
    missing = [r for r in range(max(v["ranks"]) + 1)
               if r not in v["ranks"]]
    if missing:
        print(f"  no dump from rank(s) {missing} "
              "(died before dumping and the pull found nothing)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dump_dir", help="directory of blackbox_rank*.json")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as JSON instead of text")
    args = ap.parse_args(argv)
    v = analyze(args.dump_dir)
    if v is None:
        print(f"hvd_postmortem: no loadable blackbox_rank*.json in "
              f"{args.dump_dir}", file=sys.stderr)
        return 1
    if args.json:
        json.dump(v, sys.stdout, indent=2, sort_keys=True)
        print()
    else:
        _print_verdict(v)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
