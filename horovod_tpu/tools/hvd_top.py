#!/usr/bin/env python3
"""``hvd_top``: live terminal dashboard over the gang telemetry view.

Fetches ``GET /gang/metrics.json`` from the rank-0 debug server (the
gang aggregator's latest fold, telemetry/aggregate.py) and renders one
row per rank — interval step rate, collective p50/p99, straggler skew,
cumulative transport bytes, queue depth, and any anomaly alerts naming
the rank — refreshing in place like ``top``.

Usage::

    hvd-top [--addr HOST:PORT] [--interval S]
    hvd-top --once [--json]      # one fetch; --json emits the raw view

``--addr`` defaults to ``127.0.0.1:$HVD_METRICS_PORT`` (the coordinator
binds ``HVD_METRICS_PORT + local_rank``, and rank 0 is local rank 0 on
its host).  ``--once --json`` prints exactly the aggregator's view, so
scripts see the same document the fleet router reads from the KV mirror
(``gang/metrics``).

Routing a "training suddenly slow" report: run ``hvd_top``, read the
ALERTS column (throughput_collapse / straggler_skew name the rank), then
``hvd_trace analyze`` that rank's span file for the phase breakdown —
see docs/troubleshooting.md.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

_CLEAR = "\x1b[2J\x1b[H"


def default_addr() -> str:
    port = os.environ.get("HVD_METRICS_PORT", "")
    return f"127.0.0.1:{port}" if port else "127.0.0.1:9090"


def fetch(addr: str, timeout: float = 2.0) -> dict:
    """The aggregator's current gang view (raises on unreachable/404)."""
    base = addr if "://" in addr else f"http://{addr}"
    with urllib.request.urlopen(f"{base}/gang/metrics.json",
                                timeout=timeout) as resp:
        view = json.loads(resp.read().decode("utf-8"))
    if not isinstance(view, dict):
        raise ValueError(f"unexpected gang view from {addr}")
    return view


def render(view: dict) -> str:
    """The dashboard as one printable string (tested without a tty)."""
    lines = []
    alerts = view.get("alerts", [])
    stale = view.get("stale_ranks", [])
    status = "ALERTING" if alerts else ("DEGRADED" if stale else "ok")
    lines.append(
        f"hvd_top — gang of {view.get('size', '?')} "
        f"(epoch {view.get('epoch', 0)}, fold #{view.get('seq', 0)}) "
        f"status: {status}")
    if stale:
        lines.append(f"  stale ranks: {stale}")
    for a in alerts:
        lines.append(
            f"  ALERT {a.get('rule')}: rank {a.get('rank')} "
            f"value={a.get('value')} baseline={a.get('baseline')} "
            f"(since fold #{a.get('since_seq')})")
    lines.append("")
    lines.append(f"{'RANK':>4} {'STEP/S':>8} {'P50ms':>8} {'P99ms':>8} "
                 f"{'SKEWms':>8} {'XPORT MB':>10} {'QUEUE':>6}  ALERTS")
    for row in view.get("per_rank", []):
        if row.get("stale"):
            lines.append(f"{row['rank']:>4} {'—  stale (no snapshot)':>46}")
            continue
        lines.append(
            f"{row['rank']:>4} {row['step_rate']:>8.2f} "
            f"{row['coll_p50_ms']:>8.2f} {row['coll_p99_ms']:>8.2f} "
            f"{row['skew_ms']:>8.2f} {row['transport_mb']:>10.2f} "
            f"{row['queue']:>6}  {','.join(row.get('alerts', [])) or '-'}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--addr", default=default_addr(),
                    help="rank-0 debug server (default: "
                         "127.0.0.1:$HVD_METRICS_PORT)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="render one fetch and exit")
    ap.add_argument("--json", action="store_true",
                    help="with --once: print the raw gang view JSON")
    args = ap.parse_args(argv)

    if args.once:
        try:
            view = fetch(args.addr)
        except Exception as e:
            print(f"hvd_top: no gang view at {args.addr}: {e}",
                  file=sys.stderr)
            return 1
        if args.json:
            json.dump(view, sys.stdout, sort_keys=True)
            print()
        else:
            print(render(view))
        return 0

    while True:
        try:
            body = render(fetch(args.addr))
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            body = f"hvd_top: waiting for gang view at {args.addr} ({e})"
        sys.stdout.write(_CLEAR + body + "\n")
        sys.stdout.flush()
        try:
            time.sleep(max(0.2, args.interval))
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    raise SystemExit(main())
