"""Periodic metrics flusher: JSONL file + rendezvous KV publication.

A daemon thread snapshots the registry every ``HVD_METRICS_INTERVAL``
seconds (default 10) and

- appends one JSON object per flush to ``HVD_METRICS_FILE`` (offline
  analysis: each line round-trips through ``json.loads``), and
- publishes the same snapshot to the rendezvous KV store under
  ``metrics/<rank>`` when a rendezvous server is in play
  (``HVD_RENDEZVOUS_ADDR``/``PORT``) — so the launcher host can read
  every rank's numbers from one place without reaching worker ports.

Flush failures are logged once per kind and never propagate: telemetry
must not take down training.  ``flush_once`` is the synchronous unit the
thread loops on, exposed for tests and for a final flush at stop.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

from horovod_tpu.telemetry import registry as _reg

log = logging.getLogger("horovod_tpu.telemetry")


class Flusher:
    def __init__(self, rank: int, path: str = "",
                 interval_s: float = 10.0, kv=None,
                 scrape: str = "", epoch: int = 0):
        self.rank = rank
        self.path = path
        self.interval_s = max(0.1, interval_s)
        self.kv = kv  # KVClient or None
        # Stamped on every record: the rank's own debug-server address
        # (the gang aggregator's direct-scrape fallback when the KV
        # entry goes missing) and the elastic epoch (so the aggregator
        # rejects a pre-re-form incarnation's numbers as stale).
        self.scrape = scrape
        self.epoch = int(epoch)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._warned = set()
        self._seq = 0

    def flush_once(self) -> Optional[dict]:
        snap = _reg.snapshot()
        if not snap:
            return None
        record = {"rank": self.rank, "seq": self._seq,
                  "epoch": self.epoch, **snap}
        if self.scrape:
            record["scrape"] = self.scrape
        self._seq += 1
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(record) + "\n")
            except OSError as e:
                self._warn_once("file", f"{self.path}: {e}")
        if self.kv is not None:
            try:
                self.kv.put(f"metrics/{self.rank}", json.dumps(record))
            except Exception as e:
                self._warn_once("kv", str(e))
        return record

    def _warn_once(self, kind: str, detail: str) -> None:
        if kind not in self._warned:
            self._warned.add(kind)
            log.warning("metrics flush (%s) failing: %s", kind, detail)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.flush_once()

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="hvd-metrics-flush", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.flush_once()  # final state always lands


def kv_from_env():
    """A KVClient for the job's rendezvous server, or ``None`` outside a
    launched job.  Imported lazily: the runner package pulls in config
    machinery workers don't otherwise need."""
    addr = os.environ.get("HVD_RENDEZVOUS_ADDR", "")
    port = os.environ.get("HVD_RENDEZVOUS_PORT", "")
    if not addr or not port:
        return None
    try:
        from horovod_tpu.runner.http_client import KVClient

        return KVClient(addr, int(port))
    except Exception:
        return None
