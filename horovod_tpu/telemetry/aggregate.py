"""Gang-wide telemetry aggregation and streaming anomaly alerts.

Every rank already exports a full snapshot (per-rank ``/metrics``, the
JSONL flusher, the KV publication under ``metrics/<rank>``); this module
is the coordinator-side fold that turns them into ONE gang view — the
online half of the offline timeline/stall analysis, run continuously:

- counters are summed across ranks,
- gauges keep their per-rank values plus min/median/max rollups,
- histograms merge *exactly* bucket-by-bucket (the registry's fixed log2
  bounds line up across ranks by construction), so the gang-wide
  p50/p99 of ``hvd_ring_hop_seconds``, ``hvd_collective_latency_seconds``
  and the serve SLO histograms are real quantiles, not averages of
  per-rank averages.

The fold reads each rank's newest flushed record from the rendezvous KV
(``metrics/<rank>``) first and falls back to scraping the rank's own
debug server (``/metrics.json`` at the address the record advertises).
A missing, torn, or old-epoch record and an unreachable scrape degrade
that rank to ``stale_ranks`` — never an exception, never a hung fold
(chaos site ``agg.scrape``).  The result is served by the rank-0 debug
server as ``GET /gang/metrics`` (Prometheus text), ``/gang/metrics.json``
and ``/gang/health``, and mirrored into the KV under ``gang/metrics``
for the fleet router.

On top of the stream, an anomaly engine evaluates EWMA-based rules each
fold (``ALERT_RULES``; knobs ``HVD_ALERT_*`` in utils/env.py).  A rule's
rising edge emits an ``ALERT`` timeline record, a blackbox event, and
``hvd_alerts_total{rule}`` — so a throughput regression fires during
warmup steps, online, instead of days later in an offline bench diff.

Zero-cost when off: with ``HVD_METRICS`` unset nothing here is imported
on any hot path, no thread starts, and no clock is read — pinned by
tests/test_aggregate.py the same way the registry hooks are.
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.telemetry import registry as _reg
from horovod_tpu.utils import env as _env

log = logging.getLogger("horovod_tpu.telemetry")

# Every rule the anomaly engine can fire, in evaluation order.  Each
# name must appear in the docs/metrics.md rule table
# (tools/check_metric_docs.py enforces it, like the metric registry).
ALERT_RULES = (
    "throughput_collapse",
    "straggler_skew",
    "queue_growth",
    "retry_spike",
    "serve_p99_breach",
)

# A scrape must never hang the fold: the KV client has its own retry
# deadline, and the direct HTTP fallback gets this socket timeout.
_SCRAPE_TIMEOUT_S = 1.0

# Absolute floors below which the growth rules (queue_growth,
# retry_spike) never fire — a queue going 0 -> 2 or one stray KV retry
# is noise, not an anomaly.
_QUEUE_FLOOR = 4
_RETRY_FLOOR = 4.0

_RANK_LABEL_RE = re.compile(r'rank="([^"]+)"')


# -- pure fold machinery (no clocks, no I/O; unit-tested directly) --------


def _matches(series: str, name: str) -> bool:
    return series == name or series.startswith(name + "{")


def _sum_series(table: Dict[str, float], name: str) -> float:
    return sum(v for k, v in table.items() if _matches(k, name))


def merge_histograms(hists: List[dict]) -> dict:
    """Exact bucket-by-bucket merge of snapshot-form histograms with
    identical bounds (the registry guarantees that per metric name)."""
    buckets: Dict[str, int] = {}
    total_sum = 0.0
    count = 0
    for h in hists:
        for b, n in h.get("buckets", {}).items():
            buckets[b] = buckets.get(b, 0) + int(n)
        total_sum += float(h.get("sum", 0.0))
        count += int(h.get("count", 0))
    return {"buckets": buckets, "sum": total_sum, "count": count}


def _merged_series(hists: Dict[str, dict], name: str) -> dict:
    return merge_histograms(
        [h for k, h in hists.items() if _matches(k, name)])


def hist_delta(cur: dict, prev: Optional[dict]) -> dict:
    """The observations ``cur`` gained since ``prev`` (bucketwise; a
    counter reset clamps to the current value instead of going
    negative)."""
    if not prev:
        return dict(cur, buckets=dict(cur.get("buckets", {})))
    pb = prev.get("buckets", {})
    buckets = {b: max(0, int(n) - int(pb.get(b, 0)))
               for b, n in cur.get("buckets", {}).items()}
    return {
        "buckets": buckets,
        "sum": max(0.0, float(cur.get("sum", 0.0))
                   - float(prev.get("sum", 0.0))),
        "count": max(0, int(cur.get("count", 0))
                     - int(prev.get("count", 0))),
    }


def fold(snaps: Dict[int, dict]) -> dict:
    """Fold per-rank registry snapshots into the gang view: counters
    summed, gauges per-rank + min/median/max, histograms merged exactly
    with gang-wide p50/p99 attached.  Pure — callers own staleness,
    rates, and alerting."""
    counters: Dict[str, float] = {}
    gauge_ranks: Dict[str, Dict[int, float]] = {}
    hists: Dict[str, List[dict]] = {}
    for rank in sorted(snaps):
        snap = snaps[rank]
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + float(v)
        for k, v in snap.get("gauges", {}).items():
            gauge_ranks.setdefault(k, {})[rank] = float(v)
        for k, h in snap.get("histograms", {}).items():
            hists.setdefault(k, []).append(h)
    gauges = {}
    for k, per in sorted(gauge_ranks.items()):
        vals = sorted(per.values())
        gauges[k] = {
            "per_rank": {str(r): per[r] for r in sorted(per)},
            "min": vals[0],
            "median": _reg.quantile(vals, 0.5),
            "max": vals[-1],
        }
    histograms = {}
    for k, hs in sorted(hists.items()):
        merged = merge_histograms(hs)
        merged["p50"] = _reg.histogram_quantile(merged, 0.50)
        merged["p99"] = _reg.histogram_quantile(merged, 0.99)
        histograms[k] = merged
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


def _with_rank(series: str, rank: str) -> str:
    if series.endswith("}"):
        return f'{series[:-1]},rank="{rank}"}}'
    return f'{series}{{rank="{rank}"}}'


def render_prometheus(view: dict) -> str:
    """Prometheus text exposition (v0.0.4) of a gang view: counters are
    the gang sums, gauges fan out per rank via an injected ``rank``
    label, histograms are the exact merges."""
    lines: List[str] = []

    def _header(base: str, kind: str) -> None:
        spec = _reg.KNOWN_METRICS.get(base)
        if spec is not None:
            lines.append(f"# HELP {base} {spec['help']}")
        lines.append(f"# TYPE {base} {kind}")

    seen = set()
    for key in sorted(view.get("counters", {})):
        base = key.split("{", 1)[0]
        if base not in seen:
            seen.add(base)
            _header(base, "counter")
        lines.append(f"{key} {_reg._fmt(view['counters'][key])}")
    for key in sorted(view.get("gauges", {})):
        base = key.split("{", 1)[0]
        if base not in seen:
            seen.add(base)
            _header(base, "gauge")
        for r, v in view["gauges"][key]["per_rank"].items():
            lines.append(f"{_with_rank(key, r)} {_reg._fmt(v)}")
    for key in sorted(view.get("histograms", {})):
        base = key.split("{", 1)[0]
        if base not in seen:
            seen.add(base)
            _header(base, "histogram")
        h = view["histograms"][key]
        finite = sorted(
            ((float(b), n) for b, n in h["buckets"].items()
             if b != "+Inf"))
        cum = 0
        suffix = key[len(base):]
        for b, n in finite:
            cum += n
            le = _reg._fmt(b)
            inner = (suffix[1:-1] + "," if suffix else "") + f'le="{le}"'
            lines.append(f"{base}_bucket{{{inner}}} {cum}")
        cum += h["buckets"].get("+Inf", 0)
        inner = (suffix[1:-1] + "," if suffix else "") + 'le="+Inf"'
        lines.append(f"{base}_bucket{{{inner}}} {cum}")
        lines.append(f"{base}_sum{suffix} {_reg._fmt(h['sum'])}")
        lines.append(f"{base}_count{suffix} {h['count']}")
    return "\n".join(lines) + "\n"


# -- anomaly engine -------------------------------------------------------


class _Ewma:
    """Trailing baseline: ``n`` counts the folds observed (the warmup
    gate), ``value`` the exponentially weighted mean."""

    __slots__ = ("alpha", "value", "n")

    def __init__(self, alpha: float):
        self.alpha = alpha
        self.value: Optional[float] = None
        self.n = 0

    def update(self, x: float) -> None:
        self.n += 1
        if self.value is None:
            self.value = float(x)
        else:
            self.value += self.alpha * (float(x) - self.value)

    def ready(self, warmup: int) -> bool:
        return self.value is not None and self.n >= warmup


class GangAggregator:
    """Coordinator-side fold of every rank's metrics snapshot into one
    gang view, plus the streaming anomaly engine.

    ``poll_once`` is the synchronous unit the daemon thread loops on,
    exposed for tests (pass ``now`` for deterministic interval rates).
    """

    def __init__(self, size: int, kv=None,
                 scrape_addrs: Optional[Dict[int, str]] = None,
                 interval_s: Optional[float] = None, epoch: int = 0,
                 check_epoch: bool = True):
        self.size = int(size)
        self.kv = kv
        self.interval_s = (interval_s if interval_s is not None
                           else _env.agg_interval_s())
        self.epoch = int(epoch)
        self.check_epoch = check_epoch
        self._addrs: Dict[int, str] = dict(scrape_addrs or {})
        self._lock = threading.Lock()
        self._view: dict = {}
        self._prev_snaps: Dict[int, dict] = {}
        self._prev_t: Optional[float] = None
        self._seq = 0
        self._warned: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Anomaly state: one EWMA per rule stream (straggler_skew keys
        # per rank), active-breach map for edge detection.
        self._alpha = _env.alert_ewma_alpha()
        self._warmup = _env.alert_warmup()
        self._ewma: Dict[str, _Ewma] = {}
        self._active: Dict[str, dict] = {}

    # -- per-rank snapshot acquisition -----------------------------------

    def _read_rank(self, rank: int) -> Optional[dict]:
        """The rank's newest snapshot record, or ``None`` (-> stale).
        KV ``metrics/<rank>`` first; direct ``/metrics.json`` scrape of
        the rank's debug server second.  Never raises, never hangs."""
        try:
            _fi.fire("agg.scrape", str(rank))
        except Exception:
            return None
        rec = None
        if self.kv is not None:
            try:
                raw = self.kv.get(f"metrics/{rank}")
            except Exception:
                raw = None
            if raw:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    rec = None  # torn write
        if isinstance(rec, dict) and rec.get("scrape"):
            self._addrs[rank] = str(rec["scrape"])
        if isinstance(rec, dict) and self.check_epoch and \
                "epoch" in rec and int(rec["epoch"]) != self.epoch:
            rec = None  # a pre-re-form incarnation's numbers
        if not isinstance(rec, dict) or "counters" not in rec:
            rec = self._scrape(rank)
        return rec if isinstance(rec, dict) else None

    def _scrape(self, rank: int) -> Optional[dict]:
        addr = self._addrs.get(rank)
        if not addr:
            return None
        try:
            with urllib.request.urlopen(
                    f"http://{addr}/metrics.json",
                    timeout=_SCRAPE_TIMEOUT_S) as resp:
                snap = json.loads(resp.read().decode("utf-8"))
        except Exception:
            return None
        if not isinstance(snap, dict) or "counters" not in snap:
            return None
        return {"rank": rank, **snap}

    # -- the fold --------------------------------------------------------

    def poll_once(self, now: Optional[float] = None) -> dict:
        t0 = time.monotonic()
        if now is None:
            now = t0
        snaps: Dict[int, dict] = {}
        stale: List[int] = []
        for r in range(self.size):
            rec = self._read_rank(r)
            if rec is None:
                stale.append(r)
            else:
                snaps[r] = rec
        view = fold(snaps)
        dt = (now - self._prev_t) if self._prev_t is not None else None
        rows = self._per_rank_rows(snaps, stale, dt)
        self._evaluate_rules(snaps, rows, dt)
        for row in rows:
            row["alerts"] = sorted(
                rule for rule, info in self._active.items()
                if info.get("rank") == row["rank"])
        self._seq += 1
        view.update({
            "seq": self._seq,
            "epoch": self.epoch,
            "size": self.size,
            "ranks": sorted(snaps),
            "stale_ranks": stale,
            "per_rank": rows,
            "alerts": [dict(info, rule=rule) for rule, info
                       in sorted(self._active.items())],
        })
        _reg.set_gauge("hvd_gang_stale_ranks", len(stale))
        with self._lock:
            self._view = view
            self._prev_snaps = snaps
            self._prev_t = now
        if self.kv is not None:
            try:
                self.kv.put("gang/metrics", json.dumps(view))
            except Exception as e:
                self._warn_once("mirror", str(e))
        _reg.observe("hvd_gang_agg_fold_seconds", time.monotonic() - t0)
        return view

    def _per_rank_rows(self, snaps: Dict[int, dict], stale: List[int],
                       dt: Optional[float]) -> List[dict]:
        """The hvd_top table: one row per rank with interval step rate,
        collective p50/p99, straggler skew, transport bytes, and queue
        depth."""
        skew_ms = self._skew_by_rank(snaps)
        rows = []
        for r in range(self.size):
            if r in stale:
                rows.append({"rank": r, "stale": True, "step_rate": 0.0,
                             "coll_p50_ms": 0.0, "coll_p99_ms": 0.0,
                             "skew_ms": 0.0, "transport_mb": 0.0,
                             "queue": 0, "alerts": []})
                continue
            snap = snaps[r]
            counters = snap.get("counters", {})
            gauges = snap.get("gauges", {})
            hists = snap.get("histograms", {})
            coll = _sum_series(counters, "hvd_collectives_total")
            prev = self._prev_snaps.get(r)
            rate = 0.0
            if dt and prev is not None:
                prev_coll = _sum_series(prev.get("counters", {}),
                                        "hvd_collectives_total")
                rate = max(0.0, coll - prev_coll) / dt
            lat = _merged_series(hists, "hvd_collective_latency_seconds")
            if prev is not None:
                lat_d = hist_delta(lat, _merged_series(
                    prev.get("histograms", {}),
                    "hvd_collective_latency_seconds"))
                if lat_d["count"]:
                    lat = lat_d
            rows.append({
                "rank": r,
                "stale": False,
                "step_rate": round(rate, 2),
                "coll_p50_ms": round(
                    1e3 * _reg.histogram_quantile(lat, 0.50), 3),
                "coll_p99_ms": round(
                    1e3 * _reg.histogram_quantile(lat, 0.99), 3),
                "skew_ms": round(skew_ms.get(r, 0.0), 3),
                "transport_mb": round(_sum_series(
                    counters, "hvd_transport_bytes_total") / 1e6, 3),
                "queue": int(gauges.get("hvd_queue_depth", 0)
                             + gauges.get("hvd_serve_queue_depth", 0)),
                "alerts": [],
            })
        return rows

    def _skew_by_rank(self, snaps: Dict[int, dict]) -> Dict[int, float]:
        """Interval mean negotiation skew per implicated rank, in ms,
        from the coordinator's labeled ``hvd_straggler_skew_seconds``
        histogram (the straggler detector runs on rank 0 only)."""
        snap = snaps.get(0)
        if snap is None:
            return {}
        prev = self._prev_snaps.get(0) or {}
        out: Dict[int, float] = {}
        for k, h in snap.get("histograms", {}).items():
            if not _matches(k, "hvd_straggler_skew_seconds"):
                continue
            m = _RANK_LABEL_RE.search(k)
            if m is None:
                continue
            d = hist_delta(h, prev.get("histograms", {}).get(k))
            use = d if d["count"] else h
            if use["count"]:
                out[int(m.group(1))] = 1e3 * use["sum"] / use["count"]
        return out

    # -- anomaly rules ---------------------------------------------------

    def _stream(self, key: str) -> _Ewma:
        e = self._ewma.get(key)
        if e is None:
            e = self._ewma[key] = _Ewma(self._alpha)
        return e

    def _check(self, key: str, value: float, breach) -> Tuple[bool, float]:
        """Evaluate ``value`` against the stream's pre-update baseline;
        a breach freezes the baseline (a collapsed interval must not
        drag the EWMA down to meet it).  Returns (breached, baseline)."""
        e = self._stream(key)
        if e.ready(self._warmup) and breach(value, e.value):
            return True, e.value
        e.update(value)
        return False, e.value if e.value is not None else value

    def _evaluate_rules(self, snaps: Dict[int, dict], rows: List[dict],
                        dt: Optional[float]) -> None:
        breaches: Dict[str, dict] = {}

        if dt and dt > 0:
            # throughput_collapse: gang collective rate vs baseline;
            # names the slowest rank.
            rates = {row["rank"]: row["step_rate"] for row in rows
                     if not row["stale"]}
            gang_rate = sum(rates.values())
            frac = _env.alert_collapse_frac()
            hit, base = self._check(
                "throughput", gang_rate,
                lambda v, b: b > 0 and v < frac * b)
            if hit:
                slowest = min(rates, key=rates.get) if rates else -1
                breaches["throughput_collapse"] = {
                    "rank": slowest, "value": round(gang_rate, 2),
                    "baseline": round(base, 2)}

            # retry_spike: gang-wide ladder + KV retry count this fold.
            retries = 0.0
            for snap in snaps.values():
                c = snap.get("counters", {})
                retries += (_sum_series(c, "hvd_kv_retries_total")
                            + _sum_series(c, "hvd_hop_retries_total"))
            prev_retries = 0.0
            for snap in self._prev_snaps.values():
                c = snap.get("counters", {})
                prev_retries += (
                    _sum_series(c, "hvd_kv_retries_total")
                    + _sum_series(c, "hvd_hop_retries_total"))
            d_retries = max(0.0, retries - prev_retries)
            rfac = _env.alert_retry_factor()
            hit, base = self._check(
                "retry", d_retries,
                lambda v, b: v >= _RETRY_FLOOR and v > rfac * max(b, 1.0))
            if hit:
                breaches["retry_spike"] = {
                    "rank": -1, "value": d_retries,
                    "baseline": round(base, 2)}

        # straggler_skew: per implicated rank, interval mean skew vs
        # that rank's own baseline, gated by the absolute floor.
        sfac = _env.alert_skew_factor()
        floor = _env.alert_skew_floor_ms()
        worst = None
        for row in rows:
            if row["stale"] or row["skew_ms"] <= 0:
                continue
            hit, base = self._check(
                f"skew/{row['rank']}", row["skew_ms"],
                lambda v, b: v > floor and v > sfac * max(b, 1e-9))
            if hit and (worst is None or row["skew_ms"] > worst["value"]):
                worst = {"rank": row["rank"], "value": row["skew_ms"],
                         "baseline": round(base, 3)}
        if worst is not None:
            breaches["straggler_skew"] = worst

        # queue_growth: deepest admission queue across ranks.
        depths = {row["rank"]: row["queue"] for row in rows
                  if not row["stale"]}
        if depths:
            deepest = max(depths, key=depths.get)
            qfac = _env.alert_queue_factor()
            hit, base = self._check(
                "queue", float(depths[deepest]),
                lambda v, b: v >= _QUEUE_FLOOR and v > qfac * max(b, 1.0))
            if hit:
                breaches["queue_growth"] = {
                    "rank": deepest, "value": depths[deepest],
                    "baseline": round(base, 2)}

        # serve_p99_breach: fixed SLO ceiling on the interval's merged
        # decode-step p99 (0 = off; no baseline needed).
        slo_ms = _env.alert_serve_p99_ms()
        if slo_ms > 0:
            cur = fold(snaps)["histograms"].get(
                "hvd_serve_token_latency_seconds")
            prev = fold(self._prev_snaps)["histograms"].get(
                "hvd_serve_token_latency_seconds") \
                if self._prev_snaps else None
            if cur is not None:
                d = hist_delta(cur, prev)
                use = d if d["count"] else cur
                p99_ms = 1e3 * _reg.histogram_quantile(use, 0.99)
                if use["count"] and p99_ms > slo_ms:
                    breaches["serve_p99_breach"] = {
                        "rank": 0, "value": round(p99_ms, 3),
                        "baseline": slo_ms}

        for rule, info in breaches.items():
            if rule not in self._active:  # rising edge
                self._fire(rule, info)
            info["since_seq"] = self._active.get(
                rule, {}).get("since_seq", self._seq + 1)
        self._active = {rule: info for rule, info in breaches.items()}

    def _fire(self, rule: str, info: dict) -> None:
        from horovod_tpu.telemetry import blackbox as _bb
        from horovod_tpu.utils import timeline as _tl

        _reg.inc_counter("hvd_alerts_total", labels=(rule,))
        _tl.engine_event(_tl.ALERT, rule=rule, rank=info["rank"],
                         value=info["value"], baseline=info["baseline"])
        _bb.note("alert", 0, rule=rule, rank=info["rank"],
                 value=info["value"])
        log.warning("gang alert: %s (rank %s, value %s, baseline %s)",
                    rule, info["rank"], info["value"], info["baseline"])

    # -- serving surface -------------------------------------------------

    def view(self) -> dict:
        with self._lock:
            return self._view

    def health(self) -> dict:
        with self._lock:
            view = self._view
        alerts = view.get("alerts", [])
        stale = view.get("stale_ranks", [])
        status = "ok"
        if stale:
            status = "degraded"
        if alerts:
            status = "alerting"
        return {"status": status, "seq": view.get("seq", 0),
                "epoch": self.epoch, "size": self.size,
                "stale_ranks": stale, "alerts": alerts}

    def render(self) -> str:
        return render_prometheus(self.view())

    # -- lifecycle -------------------------------------------------------

    def _warn_once(self, kind: str, detail: str) -> None:
        if kind not in self._warned:
            self._warned.add(kind)
            log.warning("gang aggregator (%s) failing: %s", kind, detail)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception as e:  # observability never kills training
                self._warn_once("fold", repr(e))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="hvd-gang-agg", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


# -- module surface (the blackbox.get() pattern: one global, the debug
#    server reaches the live aggregator through it) -----------------------

_AGG: Optional[GangAggregator] = None


def get() -> Optional[GangAggregator]:
    return _AGG


def configure(agg: Optional[GangAggregator]) -> None:
    global _AGG
    _AGG = agg


def start_from_env(size: int, kv=None) -> Optional[GangAggregator]:
    """Rank-0 hook: build, register, and start the aggregator thread.
    Idempotent across elastic re-entry (a live aggregator is kept)."""
    global _AGG
    if _AGG is not None:
        return _AGG
    epoch = _env.get_int(_env.ELASTIC_EPOCH, 0)
    agg = GangAggregator(size, kv=kv, epoch=epoch)
    _AGG = agg
    agg.start()
    return agg


def stop() -> None:
    global _AGG
    agg = _AGG
    _AGG = None
    if agg is not None:
        agg.stop()
