"""Lock-cheap metrics registry: counters, gauges, log2 histograms.

Design goals, in order:

1. **Provably zero-cost when off.**  The module-level hooks
   (``inc_counter`` / ``set_gauge`` / ``observe``) do a single global
   load + ``None`` check and return — the same contract as
   ``fault_injection.fire`` — so instrumenting a hot path costs one
   function call and zero allocations when ``HVD_METRICS`` is unset
   (pinned by tests/test_telemetry.py, mirroring the chaos harness pin).
   Call sites whose *arguments* would allocate (dynamic label values,
   byte counts) guard on ``enabled()`` first.
2. **Central registry.**  Every metric name must be declared in
   ``KNOWN_METRICS`` before use — an undeclared name raises when the
   registry is on.  ``tools/check_metric_docs.py`` lints that every
   registered name is documented in docs/metrics.md, the same three-way
   contract as the fault-site registry (tools/check_fault_sites.py).
3. **One lock, fixed buckets.**  A single ``threading.Lock`` guards all
   series (contention is negligible next to the socket work the
   instrumented paths do).  Histograms use fixed log2 bucket bounds
   (``lo * 2**i``), so an observation is a ``bisect`` + increment — no
   per-observation allocation, and buckets line up across ranks for
   aggregation.

Prometheus text exposition follows the v0.0.4 format: ``# HELP`` /
``# TYPE`` headers, cumulative ``_bucket{le=...}`` series plus ``_sum`` /
``_count`` for histograms.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, Optional, Tuple


def log2_buckets(lo: float, n: int) -> Tuple[float, ...]:
    """``n`` upper bounds ``lo * 2**i`` (the +Inf bucket is implicit)."""
    return tuple(lo * (2.0 ** i) for i in range(n))


def _counter(help_: str, labels: Tuple[str, ...] = ()) -> dict:
    return {"kind": "counter", "help": help_, "labels": labels}


def _gauge(help_: str, labels: Tuple[str, ...] = ()) -> dict:
    return {"kind": "gauge", "help": help_, "labels": labels}


def _hist(help_: str, lo: float, n: int,
          labels: Tuple[str, ...] = ()) -> dict:
    return {"kind": "histogram", "help": help_, "labels": labels,
            "buckets": log2_buckets(lo, n)}


# Bucket families: latencies span 0.5 ms .. ~16 s; sizes span
# 256 B .. 128 MB (the default fusion threshold is 64 MB).
_SECONDS = (0.0005, 16)
_BYTES = (256.0, 20)

# The registry: every metric the package emits, with kind, help text,
# label names, and (for histograms) bucket bounds.  Keep alphabetized
# within each group; docs/metrics.md must list every name here
# (tools/check_metric_docs.py enforces it).
KNOWN_METRICS: Dict[str, dict] = {
    # -- engine coordination (runtime_py.py) --
    "hvd_cycles_total": _counter(
        "Background coordination cycles run."),
    "hvd_cycle_duration_seconds": _hist(
        "Wall time of one coordination cycle.", *_SECONDS),
    "hvd_negotiation_seconds": _hist(
        "Per-tensor negotiation latency: first rank ready to globally "
        "ready.", *_SECONDS),
    "hvd_queue_depth": _gauge(
        "Requests waiting in the engine message queue at cycle start."),
    "hvd_fused_bytes": _hist(
        "Payload bytes per fused response batch.", *_BYTES),
    "hvd_fused_tensors": _hist(
        "Tensors per fused response batch.", 1.0, 10),
    "hvd_stall_warnings_total": _counter(
        "Stalled-tensor warnings issued by the stall inspector."),
    # -- collectives (ops/eager.py; the jit bridge funnels through the
    #    same eager machinery, so these cover both entry points) --
    "hvd_collectives_total": _counter(
        "Collective operations completed.", ("op", "dtype")),
    "hvd_collective_bytes": _hist(
        "Input payload bytes per collective.", *_BYTES,
        labels=("op", "dtype")),
    "hvd_collective_latency_seconds": _hist(
        "Enqueue-to-completion latency per collective.", *_SECONDS,
        labels=("op", "dtype")),
    # -- eager data plane (ops/cpu_backend.py; docs/performance.md) --
    "hvd_ring_hop_seconds": _hist(
        "Wall time of one ring hop (send enqueue through receive+reduce "
        "and send completion), labeled by ring phase.", *_SECONDS,
        labels=("phase",)),
    "hvd_dataplane_alloc_bytes": _counter(
        "Bytes allocated growing the persistent data-plane buffers "
        "(fusion, hop, and fp32 scratch); flat in steady state."),
    "hvd_transport_bytes_total": _counter(
        "Payload bytes enqueued on the eager data plane, by transport "
        "(shm for same-host peers, tcp otherwise).",
        labels=("transport",)),
    # -- response cache (common/response_cache.py via the engine) --
    "hvd_cache_hits_total": _counter(
        "Response-cache hits in request classification."),
    "hvd_cache_misses_total": _counter(
        "Response-cache misses (full negotiation taken)."),
    # -- robustness layers --
    "hvd_heartbeat_misses_total": _counter(
        "Ranks declared dead by the heartbeat timeout."),
    "hvd_evictions_total": _counter(
        "Dead ranks evicted via the Join machinery."),
    "hvd_collective_timeouts_total": _counter(
        "Collectives aborted by the gang after blowing "
        "HVD_COLLECTIVE_TIMEOUT (hung-rank detection)."),
    "hvd_collective_abort_seconds": _hist(
        "Latency from a rank's local hop timeout to the applied "
        "gang-wide abort verdict.", *_SECONDS),
    "hvd_hop_retries_total": _counter(
        "Data frames retransmitted by the recovery ladder, by cause "
        "(corrupt = CRC mismatch NACK, reset = replay after a peer "
        "reset/reconnect, failover = replay after an shm->TCP "
        "demotion).", labels=("cause",)),
    "hvd_peer_reconnects_total": _counter(
        "Dropped data sockets re-dialed and resumed in place by the "
        "recovery ladder (no eviction)."),
    "hvd_transport_failovers_total": _counter(
        "Peer pairs demoted from a faulted shm ring to TCP in place by "
        "the recovery ladder."),
    "hvd_kv_retries_total": _counter(
        "Rendezvous KV client request retries."),
    "hvd_elastic_epoch": _gauge(
        "Current elastic membership epoch."),
    "hvd_elastic_reforms_total": _counter(
        "Successful elastic gang re-forms."),
    "hvd_leader_failovers_total": _counter(
        "Re-forms triggered by the death of rank 0 (the star "
        "coordinator / serving leader); the lowest surviving rank "
        "is promoted."),
    "hvd_nonfinite_skips_total": _counter(
        "Steps skipped by the agreed non-finite gradient guard."),
    # -- hierarchical control plane (runtime_py.py two-level tree;
    #    docs/fault_tolerance.md "Hierarchical control plane") --
    "hvd_ctrl_cycle_seconds": _hist(
        "Wall time of one root coordination cycle, labeled by gang "
        "size — the coordination-cycle-latency-vs-ranks curve the "
        "control-plane scale simulation (bench.py) exports.", *_SECONDS,
        labels=("ranks",)),
    "hvd_subcoord_reparents_total": _counter(
        "Children of a dead per-host sub-coordinator re-attached "
        "directly to the root (TAG_REPARENT) without a gang-wide "
        "abort."),
    "hvd_fenced_writes_total": _counter(
        "Stale-epoch writes rejected by the epoch fence: control "
        "frames answered with TAG_FENCE by the coordinator, and "
        "elastic/* KV writes answered with HTTP 409 by the rendezvous "
        "server."),
    # -- gang-wide tracing (telemetry/trace.py; docs/timeline.md) --
    "hvd_trace_clock_skew_seconds": _gauge(
        "Latest midpoint-method estimate of this rank's monotonic-clock "
        "offset from rank 0 (TAG_CLOCK_PING over the control channel)."),
    "hvd_trace_spans_total": _counter(
        "Trace spans recorded, by span phase (negotiate, pack, hop, "
        "unpack, callback, serve.*, elastic.*, ...).", ("phase",)),
    # -- straggler detection (telemetry/straggler.py) --
    "hvd_straggler_skew_seconds": _hist(
        "Negotiation skew: last rank ready minus first rank ready, "
        "labeled by the last rank.", *_SECONDS, labels=("rank",)),
    "hvd_straggler_events_total": _counter(
        "STRAGGLER records emitted (rank consistently last beyond "
        "HVD_STRAGGLER_WARN_MS).", ("rank",)),
    # -- inference serving (serving/) --
    "hvd_serve_requests_total": _counter(
        "Serving requests by terminal status (ok / shed / error / "
        "replayed — replayed counts re-admissions after a re-form, "
        "the same request later lands in ok).", ("status",)),
    "hvd_serve_queue_depth": _gauge(
        "Requests waiting for a decode slot (rank 0)."),
    "hvd_serve_batch_occupancy": _gauge(
        "Decode slots currently serving a request (rank 0)."),
    "hvd_serve_ttft_seconds": _hist(
        "Time to first token: submit to first sampled token.",
        *_SECONDS),
    "hvd_serve_token_latency_seconds": _hist(
        "Wall time of one gang decode step (prefills + batched step + "
        "token-agreement allreduce).", *_SECONDS),
    "hvd_serve_last_step_age_seconds": _gauge(
        "Seconds since the gang last confirmed a decode step (rank 0; "
        "refreshed on each /stats read — a growing value means the gang "
        "is wedged)."),
    "hvd_serve_oldest_queued_age_seconds": _gauge(
        "Age of the oldest request still waiting for a decode slot "
        "(rank 0; 0 when the queue is empty)."),
    # -- flight recorder (telemetry/blackbox.py; docs/fault_tolerance.md) --
    "hvd_blackbox_dumps_total": _counter(
        "Flight-recorder dumps written at terminal failures."),
    # -- gang aggregation & alerts (telemetry/aggregate.py) --
    "hvd_alerts_total": _counter(
        "Anomaly-engine alerts fired (rising edges), by rule.",
        ("rule",)),
    "hvd_gang_agg_fold_seconds": _hist(
        "Wall time of one gang aggregation fold on the coordinator "
        "(read every rank's snapshot, merge, evaluate alert rules).",
        *_SECONDS),
    "hvd_gang_stale_ranks": _gauge(
        "Ranks whose snapshot could not be read in the latest "
        "aggregation fold (missing/torn/old-epoch KV entry and "
        "unreachable scrape fallback)."),
}


class Registry:
    """All live series for one process.  Series are keyed by
    ``(name, label_values)``; label values arrive as a tuple ordered
    like the spec's label names."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[tuple, float] = {}
        self._gauges: Dict[tuple, float] = {}
        # (name, labels) -> [bucket_counts..., inf_count, sum, count]
        self._hists: Dict[tuple, list] = {}

    @staticmethod
    def _spec(name: str, kind: str) -> dict:
        spec = KNOWN_METRICS.get(name)
        if spec is None:
            raise KeyError(
                f"metric {name!r} is not declared in KNOWN_METRICS "
                "(horovod_tpu/telemetry/registry.py) — declare it and "
                "document it in docs/metrics.md")
        if spec["kind"] != kind:
            raise TypeError(
                f"metric {name!r} is a {spec['kind']}, not a {kind}")
        return spec

    def inc_counter(self, name: str, value: float = 1.0,
                    labels: tuple = ()) -> None:
        self._spec(name, "counter")
        key = (name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float,
                  labels: tuple = ()) -> None:
        self._spec(name, "gauge")
        with self._lock:
            self._gauges[(name, labels)] = float(value)

    def observe(self, name: str, value: float,
                labels: tuple = ()) -> None:
        spec = self._spec(name, "histogram")
        bounds = spec["buckets"]
        idx = bisect_left(bounds, value)  # == len(bounds) -> +Inf bucket
        key = (name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = [0] * (len(bounds) + 1) + [0.0, 0]
            h[idx] += 1
            h[-2] += value
            h[-1] += 1

    # -- export ----------------------------------------------------------

    @staticmethod
    def _series(name: str, labels: tuple) -> str:
        if not labels:
            return name
        names = KNOWN_METRICS[name]["labels"]
        inner = ",".join(f'{k}="{v}"' for k, v in zip(names, labels))
        return f"{name}{{{inner}}}"

    def snapshot(self) -> dict:
        """JSON-serializable view: Prometheus-style series keys so tests
        and offline analysis can match a labeled series by name."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: list(v) for k, v in self._hists.items()}
        out = {"counters": {}, "gauges": {}, "histograms": {}}
        for (name, labels), v in sorted(counters.items()):
            out["counters"][self._series(name, labels)] = v
        for (name, labels), v in sorted(gauges.items()):
            out["gauges"][self._series(name, labels)] = v
        for (name, labels), h in sorted(hists.items()):
            bounds = KNOWN_METRICS[name]["buckets"]
            buckets = {_fmt(b): h[i] for i, b in enumerate(bounds)}
            buckets["+Inf"] = h[len(bounds)]
            out["histograms"][self._series(name, labels)] = {
                "buckets": buckets, "sum": h[-2], "count": h[-1]}
        return out

    def render_prometheus(self) -> str:
        """Text exposition format v0.0.4."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: list(v) for k, v in self._hists.items()}
        lines = []
        for name in sorted(KNOWN_METRICS):
            spec = KNOWN_METRICS[name]
            kind = spec["kind"]
            store = {"counter": counters, "gauge": gauges,
                     "histogram": hists}[kind]
            series = sorted(k for k in store if k[0] == name)
            if not series:
                continue
            lines.append(f"# HELP {name} {spec['help']}")
            lines.append(f"# TYPE {name} {kind}")
            if kind != "histogram":
                for key in series:
                    lines.append(
                        f"{self._series(name, key[1])} {_fmt(store[key])}")
                continue
            bounds = spec["buckets"]
            label_names = spec["labels"]
            for key in series:
                h = store[key]
                extra = list(zip(label_names, key[1]))
                cum = 0
                for i, b in enumerate(bounds):
                    cum += h[i]
                    lines.append(
                        f"{_labeled(name + '_bucket', extra, ('le', _fmt(b)))}"
                        f" {cum}")
                cum += h[len(bounds)]
                lines.append(
                    f"{_labeled(name + '_bucket', extra, ('le', '+Inf'))}"
                    f" {cum}")
                base = self._series(name, key[1])
                suffix = base[len(name):]  # "{...}" or ""
                lines.append(f"{name}_sum{suffix} {_fmt(h[-2])}")
                lines.append(f"{name}_count{suffix} {h[-1]}")
        return "\n".join(lines) + "\n"


# -- quantile math (shared by aggregate.py, serving /stats, bench.py) ----


def quantile(samples, q: float) -> float:
    """The ``q``-quantile (``0 <= q <= 1``) of raw samples with linear
    interpolation between order statistics — numerically identical to
    ``np.percentile(samples, 100 * q)`` so bench.py's gated numbers do
    not move when it switches over.  Empty input -> 0.0."""
    xs = sorted(float(x) for x in samples)
    if not xs:
        return 0.0
    if len(xs) == 1:
        return xs[0]
    h = (len(xs) - 1) * q
    lo = int(h)
    if lo >= len(xs) - 1:
        return xs[-1]
    return xs[lo] + (h - lo) * (xs[lo + 1] - xs[lo])


def histogram_quantile(hist: dict, q: float) -> float:
    """The ``q``-quantile (``0 <= q <= 1``) of a snapshot-form histogram
    (``{"buckets": {bound: n, ..., "+Inf": n}, "sum": ..., "count": ...}``).

    Exact for the fixed log2 buckets this registry uses, in the sense
    that it returns the smallest bucket upper bound whose cumulative
    count reaches ``q * count`` — every observation in a bucket is ``<=``
    that bound, so the reported value is a true upper bound on the real
    quantile with at most one bucket (2x) of slack, and merged per-rank
    histograms give the same answer as one gang-wide histogram would.
    Mass landing in ``+Inf`` reports the last finite bound (the result
    must stay JSON-serializable).  Empty histogram -> 0.0."""
    buckets = hist.get("buckets", {})
    bounds = sorted((float(b), int(n)) for b, n in buckets.items()
                    if b not in ("+Inf", "inf"))
    total = sum(n for _, n in bounds)
    total += int(buckets.get("+Inf", buckets.get("inf", 0)))
    if total <= 0 or not bounds:
        return 0.0
    target = q * float(total)
    cum = 0
    for b, n in bounds:
        cum += n
        if cum >= target and cum > 0:
            return b
    return bounds[-1][0]


def _fmt(v) -> str:
    """Prometheus-friendly number: integral floats print without the
    trailing ``.0`` (``le="256"`` not ``le="256.0"``)."""
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _labeled(name: str, pairs: list, *extra: tuple) -> str:
    inner = ",".join(f'{k}="{v}"' for k, v in list(pairs) + list(extra))
    return f"{name}{{{inner}}}"


# -- module-level hooks (the instrumentation surface) ---------------------
#
# Exactly the fault_injection._PLAN shape: one global, checked inline.
# When telemetry is off, _REG is None and every hook is load+test+return.

_REG: Optional[Registry] = None


def enabled() -> bool:
    return _REG is not None


def inc_counter(name: str, value: float = 1.0, labels: tuple = ()) -> None:
    reg = _REG
    if reg is None:
        return
    reg.inc_counter(name, value, labels)


def set_gauge(name: str, value: float, labels: tuple = ()) -> None:
    reg = _REG
    if reg is None:
        return
    reg.set_gauge(name, value, labels)


def observe(name: str, value: float, labels: tuple = ()) -> None:
    reg = _REG
    if reg is None:
        return
    reg.observe(name, value, labels)


def configure(on: bool = True) -> None:
    """Turn the registry on/off.  Turning on when already on keeps the
    existing series (an elastic re-form re-initializes the engine in the
    same process; counters must survive it)."""
    global _REG
    if on:
        if _REG is None:
            _REG = Registry()
    else:
        _REG = None


def get() -> Optional[Registry]:
    return _REG


def snapshot() -> dict:
    reg = _REG
    return reg.snapshot() if reg is not None else {}


def render_prometheus() -> str:
    reg = _REG
    return reg.render_prometheus() if reg is not None else ""


def known_metrics() -> Dict[str, dict]:
    """Registry accessor for tools/check_metric_docs.py."""
    return dict(KNOWN_METRICS)
