"""Always-on flight recorder: every failure ships its own evidence.

Gang-wide tracing (trace.py) is opt-in, so the 3 a.m. production
failure is exactly the run nobody traced.  This module is the black
box: a per-rank, fixed-capacity, in-memory ring of the event points the
codebase already pays for — collective begin/end, recovery-ladder rung
climbs, heartbeat misses, KV retries, elastic epoch changes, serving
step confirms, straggler records — that costs one global load + ``None``
check plus an O(1) deque append per event, and is dumped to disk only
when something terminal happens.

Recording contract (pinned by tests/test_blackbox.py and the
test_dataplane steady-state plane):

* **Always on** unless ``HVD_BLACKBOX=0``; capacity is
  ``HVD_BLACKBOX_EVENTS`` (default 512, floor 16).
* **No extra clock reads**: ``note()`` never touches ``time`` — call
  sites pass a timestamp they already took (tracer span reads, deadline
  bookkeeping), or 0 when the site has none.  Ring order disambiguates
  untimed events.
* **Zero steady-state allocations** beyond the small per-event tuple
  the bounded deque recycles capacity for — the recorder lives in the
  tracemalloc plane of test_dataplane's steady-state pin.

Dump contract:

* On any terminal event (collective-timeout verdict, eviction, wire
  corruption, engine abort, leader failover, SIGTERM) every rank
  atomically writes ``blackbox_rank<r>.json`` — ring + metrics snapshot
  + env fingerprint (secrets redacted) + last clock-offset estimate +
  in-flight collective state — into ``HVD_BLACKBOX_DIR`` (temp file +
  ``os.replace``, so a crash mid-dump leaves no torn file).
* The write is wrapped in the ``blackbox.dump`` chaos site and swallows
  every error: a full disk drops the black box, never rethrows over the
  original failure.
* The coordinator additionally pulls still-live workers' rings over the
  control channel (TAG_BLACKBOX / TAG_BLACKBOX_DUMP, runtime_py) into
  ``blackbox_rank<r>.pulled.json`` so one archive survives even when a
  rank's disk doesn't.

``tools/hvd_postmortem.py`` ingests a dump directory and names the
first-cause rank; ``GET /debug/blackbox`` on the metrics debug server
returns the live ring of a wedged-but-alive rank.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util

SCHEMA = "hvd-blackbox-v1"

# Env keys whose values never belong in a dump (the fingerprint is
# evidence, not a credential store).
_REDACT = ("SECRET", "TOKEN", "PASSWORD", "KEY")


class Blackbox:
    """One rank's flight recorder.  Appends are GIL-atomic deque writes;
    the lock only serializes dumps against snapshot reads."""

    def __init__(self, rank: int, capacity: int, out_dir: str,
                 epoch: int = 0):
        self.rank = rank
        self.capacity = capacity
        self.dir = out_dir
        self.epoch = epoch
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._clock_offset_ns = 0
        self._in_flight_name = ""
        self._in_flight_since_ns = 0
        self._dump_count = 0

    # -- O(1) recording hooks (never read the clock) ---------------------

    def note(self, kind: str, t_ns: int, fields: Optional[dict] = None
             ) -> None:
        """Append one event.  ``t_ns`` is a ``time.monotonic_ns()``-axis
        stamp the CALLER already had (0 = untimed; ring order still
        sequences it)."""
        self._ring.append((kind, t_ns, fields))

    def collective_begin(self, t_ns: int, seq: int, name: str, op: str,
                         nbytes: int, peer: int, transport: str) -> None:
        self._in_flight_name = name
        self._in_flight_since_ns = t_ns
        self._ring.append(("collective.begin", t_ns,
                           {"seq": seq, "name": name, "op": op,
                            "bytes": nbytes, "peer": peer,
                            "tp": transport}))

    def collective_end(self, t_ns: int, seq: int, ok: bool) -> None:
        self._in_flight_name = ""
        self._in_flight_since_ns = 0
        self._ring.append(("collective.end", t_ns, {"seq": seq, "ok": ok}))

    def note_clock_offset(self, offset_ns: int) -> None:
        """Latest midpoint-method estimate of (rank-0 clock − ours),
        piggybacked off the TAG_CLOCK_PONG handler.  Stored, not rung:
        the postmortem wants only the freshest value."""
        self._clock_offset_ns = int(offset_ns)

    @property
    def clock_offset_ns(self) -> int:
        return self._clock_offset_ns

    # -- snapshot + dump -------------------------------------------------

    def snapshot(self) -> dict:
        """The dump payload as a dict (also the /debug/blackbox body and
        the TAG_BLACKBOX_DUMP wire payload)."""
        events = [dict({"kind": k, "t_ns": t}, **(f or {}))
                  for k, t, f in list(self._ring)]
        in_flight = None
        name = self._in_flight_name
        if name:
            in_flight = {"name": name,
                         "since_ns": self._in_flight_since_ns}
        env = {}
        for k in sorted(os.environ):
            if not k.startswith(("HVD_", "HOROVOD_")):
                continue
            env[k] = ("<redacted>"
                      if any(s in k for s in _REDACT)
                      else os.environ[k])
        return {
            "schema": SCHEMA,
            "rank": self.rank,
            "epoch": self.epoch,
            "capacity": self.capacity,
            "wall_ns": time.time_ns(),
            "mono_ns": time.monotonic_ns(),
            "clock_offset_ns": self._clock_offset_ns,
            "in_flight": in_flight,
            "events": events,
            "metrics": _tmx.snapshot() if _tmx.enabled() else {},
            "env": env,
        }

    def dump(self, reason: str, detail: str = "") -> Optional[str]:
        """Atomically write ``blackbox_rank<r>.json``; returns the path,
        or None when the write failed.  NEVER raises — a failed dump
        must not mask the error that triggered it (``blackbox.dump``
        chaos site)."""
        with self._lock:
            try:
                doc = self.snapshot()
                doc["reason"] = reason
                doc["detail"] = detail
                path = os.path.join(self.dir,
                                    f"blackbox_rank{self.rank}.json")
                _fi.fire("blackbox.dump", path)
                os.makedirs(self.dir, exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as fh:
                    json.dump(doc, fh, separators=(",", ":"))
                os.replace(tmp, path)
                self._dump_count += 1
                _tmx.inc_counter("hvd_blackbox_dumps_total")
                return path
            except Exception:
                return None

    def dump_bytes(self, reason: str, detail: str = "") -> bytes:
        """The dump as wire payload (coordinator pull).  Never raises;
        an encoding failure degrades to a minimal valid document."""
        try:
            doc = self.snapshot()
            doc["reason"] = reason
            doc["detail"] = detail
            return json.dumps(doc, separators=(",", ":")).encode("utf-8")
        except Exception:
            return json.dumps({"schema": SCHEMA, "rank": self.rank,
                               "epoch": self.epoch, "reason": reason,
                               "events": []}).encode("utf-8")


# Process-global recorder, module-level like runtime_py's retained replay
# batch so it survives engine teardown and elastic re-forms (an abort
# tears the engine down; the evidence must not go with it).
_BB: Optional[Blackbox] = None
_SIGTERM_HOOKED = False


def from_env(rank: int, epoch: int = 0) -> Optional[Blackbox]:
    """Engine-construction hook: create (or re-adopt) the process-global
    recorder.  Re-forms keep the ring — only rank/epoch are restamped —
    so pre-failure history survives engine incarnations."""
    global _BB
    if not env_util.blackbox_enabled():
        _BB = None
        return None
    bb = _BB
    if bb is None:
        bb = Blackbox(rank, env_util.blackbox_events(),
                      env_util.blackbox_dir(), epoch=epoch)
        _BB = bb
        _install_sigterm_hook()
    else:
        bb.rank = rank
        bb.epoch = epoch
        bb.dir = env_util.blackbox_dir()
    return bb


def _install_sigterm_hook() -> None:
    """Chain a dump onto SIGTERM (the launcher's fail-fast teardown
    signal) without stealing anyone's handler.  Best-effort: off the
    main thread (or under a non-default disposition we cannot chain)
    the terminal-event dumps still cover the failure."""
    global _SIGTERM_HOOKED
    if _SIGTERM_HOOKED:
        return
    try:
        import signal

        prev = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            dump("sigterm")
            if callable(prev):
                prev(signum, frame)
            elif prev == signal.SIG_DFL:
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        _SIGTERM_HOOKED = True
    except (ValueError, OSError, RuntimeError):
        pass


def get() -> Optional[Blackbox]:
    return _BB


def active() -> bool:
    return _BB is not None


def note(kind: str, t_ns: int = 0, **fields) -> None:
    """Global recording hook: one global load + None check when off."""
    bb = _BB
    if bb is not None:
        bb.note(kind, t_ns, fields or None)


def note_clock_offset(offset_ns: int) -> None:
    bb = _BB
    if bb is not None:
        bb.note_clock_offset(offset_ns)


def dump(reason: str, detail: str = "") -> Optional[str]:
    """Global dump hook for terminal events; no-op when off, never
    raises."""
    bb = _BB
    if bb is None:
        return None
    return bb.dump(reason, detail)


def reset() -> None:
    """Test helper: drop the global recorder (and re-arm from_env)."""
    global _BB
    _BB = None
