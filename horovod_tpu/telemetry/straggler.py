"""Straggler detection from negotiation ready ticks.

The coordinator already sees every rank's readiness for every tensor
(``runtime_py._coordinator_cycle`` absorbs one request per rank per
tensor).  This detector folds those ticks into a per-negotiation skew —
last rank ready minus first rank ready — observed into the
``hvd_straggler_skew_seconds`` histogram labeled by the *last* rank.

A rank that is merely last once is noise (someone is always last); a
straggler is a rank that is **consistently** last by a material margin.
The detector flags one when the same rank has been last for
``streak_needed`` consecutive completed negotiations with skew above
``warn_ms`` (``HVD_STRAGGLER_WARN_MS``).  The engine turns the flag into
a ``STRAGGLER`` timeline record plus a throttled warning;
``hvd_straggler_events_total{rank=...}`` counts the emissions.

Coordinator-only and engine-thread-only, so no locking; the registry
hooks it calls are themselves thread-safe.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

from horovod_tpu.telemetry import registry as _reg

# Same rank last this many consecutive negotiations -> STRAGGLER.
DEFAULT_STREAK = 3


class StragglerDetector:
    """Feed ``note_ready`` per (tensor, rank) tick and ``note_complete``
    when the tensor goes globally ready; the latter returns
    ``(rank, skew_s)`` when the streak threshold trips."""

    def __init__(self, warn_ms: float, size: int,
                 streak_needed: int = DEFAULT_STREAK):
        self.warn_s = warn_ms / 1000.0
        self.size = size
        self.streak_needed = streak_needed
        # key -> {rank: first-ready monotonic tick}
        self._ready: Dict[str, Dict[int, float]] = {}
        self._streak_rank: Optional[int] = None
        self._streak = 0

    def note_ready(self, key: str, rank: int,
                   now: Optional[float] = None) -> None:
        ticks = self._ready.setdefault(key, {})
        if rank not in ticks:  # first tick wins; re-sends don't reset it
            ticks[rank] = time.monotonic() if now is None else now

    def note_complete(self, key: str) -> Optional[Tuple[int, float]]:
        ticks = self._ready.pop(key, None)
        if not ticks or len(ticks) < 2:
            return None
        last_rank = max(ticks, key=ticks.get)
        skew = ticks[last_rank] - min(ticks.values())
        _reg.observe("hvd_straggler_skew_seconds", skew,
                     labels=(str(last_rank),))
        # warn_ms == 0 -> histogram-only mode, no STRAGGLER records.
        if self.warn_s <= 0 or skew <= self.warn_s:
            self._streak_rank, self._streak = None, 0
            return None
        if last_rank == self._streak_rank:
            self._streak += 1
        else:
            self._streak_rank, self._streak = last_rank, 1
        if self._streak < self.streak_needed:
            return None
        self._streak = 0  # re-arm: one record per full streak
        _reg.inc_counter("hvd_straggler_events_total",
                         labels=(str(last_rank),))
        return last_rank, skew

    def forget(self, key: str) -> None:
        """Drop a pending negotiation (tensor evicted with its rank)."""
        self._ready.pop(key, None)
