"""horovod_tpu.telemetry: unified metrics for the whole stack.

One registry (``registry.KNOWN_METRICS``) instruments the engine's
coordination cycles, eager/bridge collectives, the response cache, and
the robustness layers (heartbeats, KV retries, elastic, integrity); on
top of it sit a per-worker Prometheus ``/metrics`` debug server, a JSONL
flusher with rendezvous KV publication, and a straggler detector.  See
docs/metrics.md for the metric table, endpoint protocol, and knobs.

Lifecycle: the engines call ``init_from_env`` at construction;
``basics.shutdown`` calls ``stop``.  ``stop`` tears down the server and
flusher but keeps the registry counting — an elastic re-form
re-initializes the engine in the same process and the counters must
span it.  ``reset`` (tests) drops everything.

Enablement: ``HVD_METRICS`` truthy, or either ``HVD_METRICS_PORT`` /
``HVD_METRICS_FILE`` set.  When none are, the instrumentation hooks are
a single global load + None check (pinned by tests/test_telemetry.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from horovod_tpu.telemetry import aggregate as _agg_mod
from horovod_tpu.telemetry import flush as _flush_mod
from horovod_tpu.telemetry import registry
from horovod_tpu.telemetry import server as _server_mod
from horovod_tpu.telemetry.registry import (  # noqa: F401
    KNOWN_METRICS,
    enabled,
    histogram_quantile,
    inc_counter,
    known_metrics,
    observe,
    quantile,
    render_prometheus,
    set_gauge,
    snapshot,
)
from horovod_tpu.telemetry.straggler import StragglerDetector  # noqa: F401
from horovod_tpu.utils import env as env_util

_lock = threading.Lock()
_server: Optional[_server_mod.MetricsServer] = None
_flusher: Optional[_flush_mod.Flusher] = None


def enabled_in_env() -> bool:
    return (env_util.get_bool(env_util.METRICS)
            or bool(env_util.get_str(env_util.METRICS_PORT))
            or bool(env_util.get_str(env_util.METRICS_FILE)))


def init_from_env(rank: int, local_rank: int = 0, size: int = 1) -> bool:
    """Engine-construction hook: turn the registry on and start the
    debug server / flusher per the env — plus, on rank 0, the gang
    aggregator that folds every rank's snapshot into the single gang
    view (``/gang/metrics*``).  Idempotent — an elastic re-form
    re-enters here with the server already up."""
    global _server, _flusher
    if not enabled_in_env():
        return False
    registry.configure(True)
    with _lock:
        if _server is None:
            port = env_util.get_int(env_util.METRICS_PORT, 0)
            if port > 0:
                _server = _server_mod.maybe_start(port, local_rank)
        kv = _flush_mod.kv_from_env()
        if _flusher is None:
            path = env_util.get_str(env_util.METRICS_FILE)
            interval = env_util.get_float(env_util.METRICS_INTERVAL, 10.0)
            if path or kv is not None:
                scrape = ""
                if _server is not None:
                    scrape = f"127.0.0.1:{_server.port}"
                _flusher = _flush_mod.Flusher(
                    rank, path=path, interval_s=interval, kv=kv,
                    scrape=scrape,
                    epoch=env_util.get_int(env_util.ELASTIC_EPOCH, 0))
                _flusher.start()
        if rank == 0 and size > 1 and kv is not None:
            _agg_mod.start_from_env(size, kv=kv)
    return True


def stop() -> None:
    """Stop the server and flusher (final flush included); the registry
    keeps its series — see module docstring."""
    global _server, _flusher
    with _lock:
        srv, fl = _server, _flusher
        _server, _flusher = None, None
    _agg_mod.stop()
    if fl is not None:
        fl.stop()
    if srv is not None:
        srv.stop()


def reset() -> None:
    """Test helper: full teardown, registry included."""
    stop()
    registry.configure(False)


def server_port() -> Optional[int]:
    srv = _server
    return srv.port if srv is not None else None
