"""Gang-wide distributed tracing: every rank streams structured spans.

The timeline (utils/timeline.py) is rank-0-only and records *that* a
collective ran; this module records *where the time went on every rank*:
one span stream per rank covering the full life of each fused collective
— ``negotiate`` (enqueue to execution start), ``pack``,
``hop[i]{send_wait, recv, reduce}``, ``unpack``, ``callback`` — plus the
serving lockstep steps (``serve.apply`` / ``serve.confirm``), elastic
``elastic.reform`` / ``elastic.replay``, and recovery-ladder
``hop.retry`` / ``transport.failover`` events, each tagged with (rank,
collective seq, transport kind, peer).

On-disk format is JSONL, one record per line (append-safe across elastic
re-forms, truncation-safe on crash):

* ``{"k": "meta", "rank": R, "epoch": E, "mono_anchor_ns": ...,
  "wall_anchor_ns": ...}`` — once per incarnation; the anchors are the
  process-wide pair from utils/timeline.py, the coarse (NTP-grade)
  cross-host alignment fallback.
* ``{"k": "clock", "offset_ns": ..., "rtt_ns": ..., "t_ns": ...}`` —
  one midpoint-method estimate of (rank-0 clock − this rank's clock),
  fed by the TAG_CLOCK_PING/PONG exchange the worker piggybacks on the
  control channel (runtime_py).  ``tools/hvd_trace.py merge`` uses the
  median estimate to fuse the per-rank streams onto rank 0's clock.
* ``{"k": "span", "ph": <phase>, "t0": ..., "t1": ..., "seq": ...,
  ...args}`` — timestamps are raw ``time.monotonic_ns()`` reads.

Collective ``seq`` is a per-tracer counter bumped by
``begin_collective()``; responses execute serially in response-stream
order on every rank, so the same seq names the same fused collective
gang-wide — no seq needs to cross the wire.

Zero-cost contract (same discipline as the metrics registry and the
fault-injection hooks): with ``HVD_TRACE`` unset, ``from_env`` returns
``None`` and every call site guards on a single attribute/global load +
``None`` check — no allocation, no clock read, no syscall (pinned by
tests/test_trace.py and the test_dataplane steady-state pins).  Span
file writes are wrapped in the ``trace.emit`` chaos site and swallow
every error: a full disk or injected fault drops spans, never training.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.telemetry import registry as _tmx
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import timeline as _tl

# Records buffered per flush: spans are tiny and bursty (one per ring
# hop), so batching keeps the writer off the hot path's syscall budget.
_FLUSH_EVERY = 64


class Tracer:
    """One rank's span stream.  Thread-safe: the background loop, the
    ctrl recv thread (clock records), and the serving thread all emit."""

    def __init__(self, rank: int, path: str, epoch: int = 0):
        self.rank = rank
        self.path = path
        self.epoch = epoch
        self._lock = threading.Lock()
        self._buf: list = []
        self._seq = -1
        self._closed = False
        self._f = None
        try:
            # Append: an elastic re-form re-opens the same rank file and
            # adds a fresh meta record; JSONL makes that well-formed.
            self._f = open(path, "a")
        except OSError:
            self._f = None  # tracing silently off; training unaffected
        self._push({"k": "meta", "rank": rank, "epoch": epoch,
                    "pid": os.getpid(),
                    "mono_anchor_ns": _tl.MONO_ANCHOR_NS,
                    "wall_anchor_ns": _tl.WALL_ANCHOR_NS})

    # -- collective sequencing ------------------------------------------

    def begin_collective(self) -> int:
        """Bump and return the collective seq.  Called once per executed
        response, in response-stream order — identical on every rank."""
        self._seq += 1
        return self._seq

    @property
    def seq(self) -> int:
        return self._seq

    # -- record emission -------------------------------------------------

    def span(self, phase: str, t0_ns: int, t1_ns: int,
             seq: Optional[int] = None, **args) -> None:
        rec = {"k": "span", "ph": phase, "t0": int(t0_ns),
               "t1": int(t1_ns),
               "seq": self._seq if seq is None else seq}
        if args:
            rec.update(args)
        self._push(rec)
        if _tmx.enabled():
            _tmx.inc_counter("hvd_trace_spans_total", 1, (phase,))

    def instant(self, phase: str, **args) -> None:
        t = time.monotonic_ns()
        self.span(phase, t, t, **args)

    def clock(self, offset_ns: int, rtt_ns: int) -> None:
        """Record one clock-offset estimate: (rank-0 clock − ours)."""
        self._push({"k": "clock", "offset_ns": int(offset_ns),
                    "rtt_ns": int(rtt_ns),
                    "t_ns": time.monotonic_ns()})

    # -- buffered writer -------------------------------------------------

    def _push(self, rec: dict) -> None:
        with self._lock:
            self._buf.append(rec)
            if len(self._buf) >= _FLUSH_EVERY:
                self._flush_locked()

    def _flush_locked(self) -> None:
        buf, self._buf = self._buf, []
        if not buf or self._f is None or self._closed:
            return
        try:
            # Chaos site: an injected error here models a full disk /
            # dead NFS mount — the batch is dropped, training continues.
            _fi.fire("trace.emit", self.path)
            self._f.write("".join(
                json.dumps(r, separators=(",", ":")) + "\n" for r in buf))
            self._f.flush()
        except Exception:
            pass

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def close(self) -> None:
        with self._lock:
            self._flush_locked()
            self._closed = True
            if self._f is not None:
                try:
                    self._f.close()
                except Exception:
                    pass
                self._f = None


# The process-global tracer: the hook for call sites that have no engine
# handle (transport build, recovery ladder, elastic re-form).  Valid in
# production (one rank per process); in-process multi-rank test harnesses
# attach per-engine Tracer instances to ``engine._tracer`` instead.
_TR: Optional[Tracer] = None


def enabled_in_env() -> bool:
    return env_util.trace_enabled()


def from_env(rank: int) -> Optional[Tracer]:
    """Engine-construction hook: a Tracer when ``HVD_TRACE`` is set
    (every rank — that is the point), else None.  Also installs the
    process-global tracer for engine-less call sites."""
    global _TR
    if not enabled_in_env():
        return None
    d = env_util.trace_dir()
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        pass
    tr = Tracer(rank, os.path.join(d, f"trace_rank{rank}.jsonl"),
                epoch=env_util.get_int(env_util.ELASTIC_EPOCH, 0))
    _TR = tr
    return tr


def get() -> Optional[Tracer]:
    return _TR


def active() -> bool:
    return _TR is not None


def emit(phase: str, t0_ns: int, t1_ns: int, **args) -> None:
    """Global-hook span: one global load + None check when off."""
    tr = _TR
    if tr is not None:
        tr.span(phase, t0_ns, t1_ns, **args)


def emit_instant(phase: str, **args) -> None:
    tr = _TR
    if tr is not None:
        tr.instant(phase, **args)


def release(tr: Optional[Tracer]) -> None:
    """Engine-shutdown hook: flush + close an engine's tracer and clear
    the global hook if it points at the same instance."""
    global _TR
    if tr is None:
        return
    tr.close()
    if _TR is tr:
        _TR = None


def reset() -> None:
    """Test helper: drop the global tracer."""
    global _TR
    tr, _TR = _TR, None
    if tr is not None:
        tr.close()
