"""Per-worker metrics debug server.

``GET /metrics`` returns the Prometheus text exposition of this worker's
registry; ``GET /metrics.json`` the JSON snapshot; ``GET /health`` is an
open liveness probe — the same trio of concerns as the rendezvous server
(runner/http_server.py), and the same ThreadingHTTPServer shape.

Each worker binds ``HVD_METRICS_PORT + local_rank`` so co-located
workers on one host don't collide; a failed bind logs a warning and the
job runs on (observability must never take down training).  The chaos
site ``metrics.server.request`` turns a request into a 503 shed,
mirroring ``kv.server.request``, so scrapers' retry behavior is testable
under tests/test_chaos.py.
"""

from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.telemetry import registry as _reg

log = logging.getLogger("horovod_tpu.telemetry")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # silence request logging
        pass

    def _chaos_unavailable(self) -> bool:
        try:
            _fi.fire("metrics.server.request", f"{self.command} {self.path}")
        except _fi.InjectedFault:
            self.send_response(503)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return True
        return False

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self._chaos_unavailable():
            return
        if self.path == "/health":
            self._send(200, b"ok", "text/plain")
            return
        if self.path == "/metrics":
            body = _reg.render_prometheus().encode("utf-8")
            self._send(200, body,
                       "text/plain; version=0.0.4; charset=utf-8")
            return
        if self.path == "/metrics.json":
            import json

            body = json.dumps(_reg.snapshot()).encode("utf-8")
            self._send(200, body, "application/json")
            return
        if self.path == "/debug/blackbox":
            # Live flight-recorder peek: the current ring as bounded
            # JSON (the ring is capacity-capped, so the body is too) —
            # a wedged-but-alive rank can be inspected without killing
            # it.  The handler thread stays responsive even while the
            # engine's background thread hangs in the data plane.
            import json

            from horovod_tpu.telemetry import blackbox as _bb

            bb = _bb.get()
            if bb is None:
                self._send(404, b'{"error": "blackbox disabled"}',
                           "application/json")
                return
            doc = bb.snapshot()
            doc["role"] = "coordinator" if bb.rank == 0 else "worker"
            body = json.dumps(doc).encode("utf-8")
            self._send(200, body, "application/json")
            return
        if self.path in ("/gang/metrics", "/gang/metrics.json",
                         "/gang/health"):
            # Gang-wide view: the live aggregator's latest fold (rank 0
            # only — other ranks run no aggregator and answer 404).
            import json

            from horovod_tpu.telemetry import aggregate as _agg

            agg = _agg.get()
            if agg is None:
                self._send(404, b'{"error": "no gang aggregator"}',
                           "application/json")
                return
            if self.path == "/gang/metrics":
                self._send(200, agg.render().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif self.path == "/gang/metrics.json":
                self._send(200, json.dumps(agg.view()).encode("utf-8"),
                           "application/json")
            else:
                self._send(200, json.dumps(agg.health()).encode("utf-8"),
                           "application/json")
            return
        self._send(404, b"", "text/plain")


class MetricsServer:
    """Threaded scrape endpoint; ``start()`` returns the bound port."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-metrics",
            daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._httpd.server_close()


def maybe_start(port: int, local_rank: int) -> Optional[MetricsServer]:
    """Bind ``port + local_rank`` and serve; on failure warn and return
    ``None`` — a taken port must not kill the worker."""
    try:
        srv = MetricsServer(port=port + local_rank)
        srv.start()
        return srv
    except OSError as e:
        log.warning("metrics server: could not bind port %d (%s); "
                    "scrape endpoint disabled for this worker",
                    port + local_rank, e)
        return None
