"""Process-group socket bootstrap, shared by the Python and native engines.

Builds the TCP topology both engines run on:

* a full **data mesh** (one socket per peer pair) for the ring data plane,
* a **control star** (worker -> rank 0) for the request/response protocol.

Rank addresses rendezvous through the launcher's HTTP KV store, mirroring
the reference's gloo rendezvous (``gloo_context.cc:56-76`` against
``run/http/http_server.py``).  This is cold-path host traffic, so it stays
in Python even for the native engine — the connected fds are handed to the
C++ core afterwards (``csrc/engine.h``), which owns them from then on.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
from typing import Dict, Optional, Tuple

from horovod_tpu.common import fault_injection as _fi
from horovod_tpu.utils import env as env_util
from horovod_tpu.utils import socketutil as su


def bootstrap_mesh(
    rank: int,
    size: int,
    rdv_addr: str,
    rdv_port: int,
    shm_capable: bool = False,
    keep_listener: bool = False,
    tree: Optional[dict] = None,
):
    """Returns ``(data, ctrl_sock, ctrl_socks, kv, prefix)``:

    * ``data``: peer rank -> connected data socket (full mesh),
    * ``ctrl_sock``: worker's connection to the coordinator (None on rank 0),
    * ``ctrl_socks``: coordinator's per-worker sockets (empty off rank 0),
    * ``kv`` / ``prefix``: the rendezvous client and key namespace, for
      post-mesh negotiation (shm transport pairing).

    ``shm_capable`` controls the host record published for transport
    selection: only engines that can speak the shm ring transport (the
    Python engine) publish a matching same-host fingerprint; everyone
    else (native engine) publishes a rank-unique token so peers always
    pair with them over TCP.

    ``keep_listener=True`` (recovery-ladder mode, ``HVD_WIRE_CRC=1``)
    appends ``(peers, listener)`` to the return tuple instead of closing
    the listener: ``peers`` maps rank -> advertised ``(host, port)`` and
    the still-open listener accepts rung-2 reconnect re-dials for the
    life of the gang (utils/ladder.py ``ReconnectListener``).

    ``tree`` (hierarchical control plane, runtime_py._plan_tree): an
    in/out dict with ``parent`` (this rank's sub-coordinator, or None)
    and ``children`` (ranks this sub-coordinator folds).  The extra
    links ride the same listener on channel 2; on return the dict gains
    ``parent_sock`` (child's uplink, or None) and ``child_socks``
    (sub-coordinator's rank -> socket map).  The return tuple shapes
    are unchanged — flat-star callers pass nothing and see nothing.
    """
    from horovod_tpu.runner.http_client import KVClient
    from horovod_tpu.utils import transport as tpt

    _fi.fire("bootstrap.start", str(rank))
    # Launcher-provided startup budget (hvdrun --start-timeout);
    # parity: HOROVOD_GLOO_TIMEOUT_SECONDS (gloo_context.cc:38-40).
    start_timeout = env_util.get_float("HVD_START_TIMEOUT", 120.0)
    kv = KVClient(rdv_addr, rdv_port)
    listener = su.listen_on()
    port = listener.getsockname()[1]
    # Optional key namespace so re-launched gangs (e.g. a retried Spark
    # barrier stage) never rendezvous against a previous attempt's stale
    # addresses on a still-running server.
    scope = os.environ.get("HVD_RDV_SCOPE", "")
    prefix = f"hvd/{scope}/" if scope else "hvd/"
    # Advertise the probed/named NIC when the launcher picked one
    # (ring-probe result or --network-interface, HVD_NIC); otherwise
    # learn the address peers can reach us at from the route the
    # rendezvous connection takes (works multi-host without NIC
    # configuration).
    my_host = None
    nic = os.environ.get("HVD_NIC")
    if nic:
        from horovod_tpu.runner.run import interface_address_any

        try:
            my_host = interface_address_any(nic)
        except ValueError:
            my_host = None  # NIC list from another host; fall back
    my_host = my_host or kv.local_address() or "127.0.0.1"
    kv.put(f"{prefix}addr/{rank}", f"{my_host}:{port}")
    # Host record for same-host transport selection (utils/transport.py).
    kv.put(f"{prefix}hostid/{rank}",
           tpt.host_record_value(rank, shm_capable))
    peers = {}
    for i in range(size):
        if i == rank:
            continue
        v = kv.wait_get(f"{prefix}addr/{i}", timeout=start_timeout)
        host, p = v.rsplit(":", 1)
        peers[i] = (host, int(p))

    # A rank connects to every lower rank and accepts from every higher
    # one; workers additionally dial a ctrl connection to rank 0.
    data: Dict[int, socket.socket] = {}
    ctrl_sock: Optional[socket.socket] = None
    ctrl_socks: Dict[int, socket.socket] = {}

    tree_parent = tree.get("parent") if tree else None
    tree_children = list(tree.get("children") or []) if tree else []

    n_accept = size - 1 - rank
    if rank == 0:
        n_accept += size - 1  # ctrl connections
    n_accept += len(tree_children)  # chan-2 tree uplinks
    accept_results: Dict[Tuple[int, int], socket.socket] = {}

    def _accept_loop():
        for _ in range(n_accept):
            s, _addr = listener.accept()
            _fi.fire("bootstrap.accept", str(rank))
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            hdr = su.recv_exact(s, 8)
            peer_rank, chan = struct.unpack("<ii", hdr)
            accept_results[(peer_rank, chan)] = s

    acceptor = threading.Thread(target=_accept_loop, daemon=True)
    acceptor.start()

    for j in range(rank):
        s = su.connect_retry(*peers[j], timeout=start_timeout)
        s.sendall(struct.pack("<ii", rank, 0))
        data[j] = s
    if rank != 0:
        s = su.connect_retry(*peers[0], timeout=start_timeout)
        s.sendall(struct.pack("<ii", rank, 1))
        ctrl_sock = s
    if tree_parent is not None:
        # Every rank keeps its direct ctrl link above; the tree uplink
        # is an ADDITIONAL channel to the same-host sub-coordinator, so
        # a dead sub-coordinator orphan can fall back to the star
        # without re-dialing anything.
        s = su.connect_retry(*peers[tree_parent], timeout=start_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.sendall(struct.pack("<ii", rank, 2))
        tree["parent_sock"] = s

    acceptor.join(timeout=start_timeout * 1.5)
    if acceptor.is_alive():
        raise ConnectionError("timed out waiting for peer connections")
    tree_child_socks: Dict[int, socket.socket] = {}
    for (peer_rank, chan), s in accept_results.items():
        if chan == 0:
            data[peer_rank] = s
        elif chan == 2:
            tree_child_socks[peer_rank] = s
        else:
            ctrl_socks[peer_rank] = s
    if tree is not None:
        tree.setdefault("parent_sock", None)
        tree["child_socks"] = tree_child_socks
    if keep_listener:
        return data, ctrl_sock, ctrl_socks, kv, prefix, peers, listener
    listener.close()
    return data, ctrl_sock, ctrl_socks, kv, prefix
